"""Multi-tenant model fleet: stacked packed serving + replicated dispatch.

The production shape of the paper's workload is one binary classifier
per cache node / segment / window generation, ALL live at once under
query traffic: the LRB-style harness retrains every window while the
previous generation keeps answering (PAPER.md; PAPERS.md "LRB").  A
solo :class:`~.packed.PackedEnsemble` serves ONE booster per jitted
program, so a fleet of M tenants would mean M servers, M program
families and M cold swaps.  This module extends the packed layout's
tree axis by a **model axis** instead:

* :class:`PackedFleet` stacks M same-shape-family boosters into one
  ``(M, T, N)`` array family (split/threshold-hi-lo/children/cat-bitset
  /leaf tables; static aux gains ``num_tenants``), so ONE jitted depth
  scan serves any ``(tenant_ids, rows)`` batch with a per-row tenant
  gather — routing is byte-identical per tenant to its solo
  ``PackedEnsemble`` because both kernels share
  :func:`~.packed.route_left`;
* a tenant **hot-swap is a device index write**
  (``lax.dynamic_update_slice`` on the model axis): when the incoming
  booster fits the fleet's pad family nothing retraces, so one tenant
  can retrain through the pipeline (PR 7) while the other M-1 keep
  answering from the same compiled program;
* :class:`FleetServer` adds **device-replicated dispatch**: the fleet
  arrays are replicated onto N local devices (the same local mesh
  ``ops/shard.py`` trains over), request micro-batch queues round-robin
  across the replicas, and each replica degrades to the host tree walk
  independently through its own
  :class:`~lightgbm_tpu.robust.retry.CircuitBreaker` — one dead chip
  dims one replica, not the fleet;
* an opt-in **bf16-quantized value variant** (``value_dtype="bf16"``)
  halves the leaf-table bytes: routing stays exact (the hi/lo
  threshold compare is untouched), only the leaf VALUES quantize —
  mirroring the training-side int8 contract (routing exact, values
  quantize; docs/Serving.md).

Telemetry (``serve.fleet.*``, docs/Observability.md): ``swap`` timing,
``swaps`` / ``swap_shape_changes`` / ``requests`` / ``rows`` /
``device_batches`` / ``device_failures`` / ``fallback_requests``
counters, per-tenant ``tenant.<m>.rows`` dispatch counters, and the
``replica_queue_depth.<r>`` / ``replica_degraded.<r>`` /
``degraded_replicas`` gauges.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from queue import Empty, Queue
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..obs import tracing
from ..robust import faults
from ..robust.retry import CircuitBreaker
from ..utils.log import LightGBMError, log_warning
from .engine import ModelMeta, _as_gbdt
from .packed import (PackedEnsemble, _prepare_rows, pack_ensemble,
                     route_left, row_bucket, tree_slice)

__all__ = ["PackedFleet", "FleetServer", "TenantHandle", "pack_fleet",
           "fleet_predict_scores", "fleet_predict_leaves"]

#: accepted ``value_dtype`` spellings -> jnp dtype of the leaf table
_VALUE_DTYPES = {"f32": jnp.float32, "float32": jnp.float32,
                 "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16}


def _value_dtype(name: str):
    try:
        return _VALUE_DTYPES[str(name).lower()]
    except KeyError:
        raise LightGBMError(
            f"unknown fleet value_dtype {name!r}; expected one of "
            f"{sorted(set(_VALUE_DTYPES))}") from None


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class PackedFleet:
    """M stacked :class:`~.packed.PackedEnsemble` tenants as one pytree.

    Every array is the solo layout with a leading model axis —
    ``(M, T, N)`` node tables, ``(M, T, L)`` leaf values, ``(M, W)``
    categorical bitset words, ``(M, T)`` stump flags.  Tenants whose
    solo pads are smaller than the fleet pads are padded up (padding
    trees are stumps with leaf value 0, padded nodes are unreachable),
    which leaves per-tenant results untouched.  The static aux
    (``num_tenants``, ``num_model``, ``max_depth``, ``num_features``,
    ``value_dtype``) rides in the treedef: equal pads AND equal aux hit
    the same jit cache entry — the index-write hot-swap zero-retrace
    contract.
    """

    split_feature: jnp.ndarray
    threshold_hi: jnp.ndarray
    threshold_lo: jnp.ndarray
    decision_type: jnp.ndarray
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    cat_start: jnp.ndarray
    cat_len: jnp.ndarray
    cat_words: jnp.ndarray
    leaf_value: jnp.ndarray
    is_stump: jnp.ndarray
    num_tenants: int = 1
    num_model: int = 1
    max_depth: int = 0
    num_features: int = 1
    value_dtype: str = "f32"

    _ARRAY_FIELDS = ("split_feature", "threshold_hi", "threshold_lo",
                     "decision_type", "left_child", "right_child",
                     "cat_start", "cat_len", "cat_words", "leaf_value",
                     "is_stump")

    def tree_flatten(self):
        children = tuple(getattr(self, f) for f in self._ARRAY_FIELDS)
        aux = (self.num_tenants, self.num_model, self.max_depth,
               self.num_features, self.value_dtype)
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    @property
    def tree_pad(self) -> int:
        return int(self.split_feature.shape[1])

    @property
    def node_pad(self) -> int:
        return int(self.split_feature.shape[2])

    @property
    def word_pad(self) -> int:
        return int(self.cat_words.shape[1])

    def shape_signature(self) -> tuple:
        """Hashable pad-family signature: a tenant swap between equal
        signatures re-dispatches into already-compiled programs."""
        return (self.split_feature.shape, self.leaf_value.shape,
                self.cat_words.shape, self.num_model, self.max_depth,
                self.num_features, self.value_dtype)

    def fits(self, pe: PackedEnsemble) -> bool:
        """Can ``pe`` be index-written into this fleet without growing
        any pad?  (The zero-retrace swap precondition.)"""
        return (pe.split_feature.shape[0] <= self.tree_pad
                and pe.split_feature.shape[1] <= self.node_pad
                and pe.cat_words.shape[0] <= self.word_pad
                and pe.max_depth <= self.max_depth
                and pe.num_model == self.num_model
                and pe.num_features == self.num_features)


def _padded_tenant_arrays(pe: PackedEnsemble, t_pad: int, n_pad: int,
                          w_pad: int, leaf_dtype) -> Tuple:
    """The solo pack's arrays padded up to the fleet pads, in
    ``PackedFleet._ARRAY_FIELDS`` order (without the leading model
    axis).  Padding trees are stumps (leaf 0 value 0 — a zero
    contribution), padded nodes/words are never reached."""
    dt = int(t_pad) - int(pe.split_feature.shape[0])
    dn = int(n_pad) - int(pe.split_feature.shape[1])
    dw = int(w_pad) - int(pe.cat_words.shape[0])
    if min(dt, dn, dw) < 0:
        raise LightGBMError("packed ensemble exceeds the fleet pads")

    def pad2(a, fill=0):
        return jnp.pad(a, ((0, dt), (0, dn)), constant_values=fill)

    return (
        pad2(pe.split_feature), pad2(pe.threshold_hi),
        pad2(pe.threshold_lo), pad2(pe.decision_type),
        pad2(pe.left_child, -1), pad2(pe.right_child, -1),
        pad2(pe.cat_start), pad2(pe.cat_len),
        jnp.pad(pe.cat_words, (0, dw)),
        jnp.pad(pe.leaf_value, ((0, dt), (0, dn))).astype(leaf_dtype),
        jnp.pad(pe.is_stump, (0, dt), constant_values=True),
    )


def stack_packs(packs: Sequence[PackedEnsemble],
                value_dtype: str = "f32") -> PackedFleet:
    """Stack solo packs (equal ``num_model``/``num_features``) into one
    :class:`PackedFleet`, padding every tenant to the fleet-wide max of
    each pad dimension."""
    if not packs:
        raise LightGBMError("stack_packs needs at least one tenant")
    k = packs[0].num_model
    nf = packs[0].num_features
    for i, pe in enumerate(packs):
        if pe.num_model != k or pe.num_features != nf:
            raise LightGBMError(
                f"tenant {i} has num_model={pe.num_model}/num_features="
                f"{pe.num_features}; the fleet requires ({k}, {nf}) — "
                f"pack every tenant with the same num_features")
    t_pad = max(int(pe.split_feature.shape[0]) for pe in packs)
    n_pad = max(int(pe.split_feature.shape[1]) for pe in packs)
    w_pad = max(int(pe.cat_words.shape[0]) for pe in packs)
    depth = max(int(pe.max_depth) for pe in packs)
    dtype = _value_dtype(value_dtype)
    cols = [jnp.stack(col) for col in zip(*[
        _padded_tenant_arrays(pe, t_pad, n_pad, w_pad, dtype)
        for pe in packs])]
    return PackedFleet(*cols, num_tenants=len(packs), num_model=k,
                       max_depth=depth, num_features=nf,
                       value_dtype=str(value_dtype).lower())


def pack_fleet(boosters: Sequence, num_features: Optional[int] = None,
               start_iteration: int = 0, num_iteration: int = -1,
               value_dtype: str = "f32"
               ) -> Tuple[PackedFleet, List[PackedEnsemble]]:
    """Pack M boosters (``Booster`` / ``GBDT`` / model-file path each)
    into a fleet.  ``num_features`` defaults to the max over tenants so
    every tenant shares one query signature.  Returns the fleet AND the
    per-tenant solo packs (the byte-identity reference; callers may
    drop them)."""
    gbdts = [_as_gbdt(b) for b in boosters]
    for g in gbdts:
        g._flush_pending()
    nf = int(num_features) if num_features else \
        max(g.max_feature_idx + 1 for g in gbdts)
    # seed-then-specialize fleets pass the SAME booster M times
    # (LGBM_FleetCreate does); pack each distinct booster once
    packed_by_id = {}
    packs = []
    for g in gbdts:
        pe = packed_by_id.get(id(g))
        if pe is None:
            pe = pack_ensemble(g.models, g.num_model,
                               start_iteration=start_iteration,
                               num_iteration=num_iteration,
                               num_features=nf)
            packed_by_id[id(g)] = pe
        packs.append(pe)
    return stack_packs(packs, value_dtype), packs


# ---------------------------------------------------------------------------
# jitted kernels: per-row tenant gather traversal + model-axis index write
# ---------------------------------------------------------------------------


def _fleet_traverse(fl: PackedFleet, tid, xhi, xlo):
    """(R, T) leaf index per (row, tree) with a per-row tenant gather;
    identical decision math to the solo kernel (shared ``route_left``),
    so each row routes exactly as its tenant's solo pack would."""
    r, t = xhi.shape[0], fl.split_feature.shape[1]
    t_ix = jnp.arange(t, dtype=jnp.int32)[None, :]
    r_ix = jnp.arange(r, dtype=jnp.int32)[:, None]
    m_ix = tid[:, None]
    node0 = jnp.where(fl.is_stump[m_ix, t_ix], -1, 0).astype(jnp.int32)

    def body(node, _):
        act = node >= 0
        cur = jnp.maximum(node, 0)
        sf = fl.split_feature[m_ix, t_ix, cur]
        left = route_left(
            fl.decision_type[m_ix, t_ix, cur],
            fl.threshold_hi[m_ix, t_ix, cur],
            fl.threshold_lo[m_ix, t_ix, cur],
            fl.cat_len[m_ix, t_ix, cur],
            lambda widx: fl.cat_words[
                m_ix, fl.cat_start[m_ix, t_ix, cur] + widx],
            xhi[r_ix, sf], xlo[r_ix, sf])
        nxt = jnp.where(left, fl.left_child[m_ix, t_ix, cur],
                        fl.right_child[m_ix, t_ix, cur])
        return jnp.where(act, nxt, node), None

    node, _ = jax.lax.scan(body, node0, None, length=fl.max_depth)
    return ~node


@jax.jit
def _fleet_scores(fl: PackedFleet, tid, xhi, xlo):
    """(K, R) float32 raw scores — traverse + per-row tenant leaf
    gather + per-class sum, one fused program for any tenant mix.  The
    bf16 variant upcasts the gathered values before the f32 sum."""
    r, t = xhi.shape[0], fl.split_feature.shape[1]
    leaves = _fleet_traverse(fl, tid, xhi, xlo)
    t_ix = jnp.arange(t, dtype=jnp.int32)[None, :]
    vals = fl.leaf_value[tid[:, None], t_ix, leaves].astype(jnp.float32)
    per_class = vals.reshape(r, t // fl.num_model, fl.num_model)
    return per_class.sum(axis=1).T


@jax.jit
def _fleet_leaves(fl: PackedFleet, tid, xhi, xlo):
    """(R, T) int32 leaf index per (row, tree) — padding trees
    included; callers slice to their tenant's real tree count."""
    return _fleet_traverse(fl, tid, xhi, xlo)


@jax.jit
def _fleet_write(fl: PackedFleet, row: PackedFleet, idx):
    """Index-write one tenant (``row`` is a ``num_tenants=1`` fleet at
    the FLEET pads) into the model axis at ``idx`` — the hot-swap
    primitive.  ``idx`` is traced, so every tenant id shares one
    compiled program."""
    ch_f, aux = fl.tree_flatten()
    ch_r, _ = row.tree_flatten()
    out = tuple(
        jax.lax.dynamic_update_slice(
            a, b.astype(a.dtype), (idx,) + (0,) * (a.ndim - 1))
        for a, b in zip(ch_f, ch_r))
    return PackedFleet.tree_unflatten(aux, out)


_fleet_scores = obs.track_jit("serve.fleet.scores", _fleet_scores)
_fleet_leaves = obs.track_jit("serve.fleet.leaves", _fleet_leaves)
_fleet_write = obs.track_jit("serve.fleet.write", _fleet_write)


def _prepare_tenants(fl: PackedFleet, tenant_ids, rows: int,
                     pad_rows: int) -> jnp.ndarray:
    """Validate + row-pad the per-row tenant ids (scalar broadcasts)."""
    tid = np.asarray(tenant_ids, np.int32)
    if tid.ndim == 0:
        tid = np.full(rows, int(tid), np.int32)
    if tid.shape != (rows,):
        raise LightGBMError(
            f"tenant_ids shape {tid.shape} does not match {rows} rows")
    if rows and (tid.min() < 0 or tid.max() >= fl.num_tenants):
        raise LightGBMError(
            f"tenant_ids must be in [0, {fl.num_tenants}); got "
            f"[{tid.min()}, {tid.max()}]")
    if pad_rows > rows:
        tid = np.pad(tid, (0, pad_rows - rows))
    return jnp.asarray(tid)


def fleet_predict_scores(fl: PackedFleet, tenant_ids, data: np.ndarray,
                         bucket_rows: bool = True,
                         min_bucket: int = 128) -> np.ndarray:
    """Raw scores (num_model, rows) float64 for a mixed-tenant batch —
    ONE device dispatch regardless of how many tenants the batch
    touches."""
    n = int(np.asarray(data).shape[0])
    if n == 0:
        return np.zeros((fl.num_model, 0), np.float64)
    pad = row_bucket(n, min_bucket) if bucket_rows else n
    tid = _prepare_tenants(fl, tenant_ids, n, pad)
    xhi, xlo, n = _prepare_rows(fl, data, pad)
    obs.inc("serve.fleet.device_batches")
    out = _fleet_scores(fl, tid, xhi, xlo)
    return np.asarray(out, np.float64)[:, :n]


def fleet_predict_leaves(fl: PackedFleet, tenant_ids, data: np.ndarray,
                         bucket_rows: bool = True,
                         min_bucket: int = 128) -> np.ndarray:
    """Leaf index (rows, tree_pad) int32 for a mixed-tenant batch;
    columns past a tenant's real tree count are padding."""
    n = int(np.asarray(data).shape[0])
    if n == 0:
        return np.zeros((0, fl.tree_pad), np.int32)
    pad = row_bucket(n, min_bucket) if bucket_rows else n
    tid = _prepare_tenants(fl, tenant_ids, n, pad)
    xhi, xlo, n = _prepare_rows(fl, data, pad)
    obs.inc("serve.fleet.device_batches")
    return np.asarray(_fleet_leaves(fl, tid, xhi, xlo), np.int32)[:n]


# ---------------------------------------------------------------------------
# FleetServer: replicated dispatch + per-tenant hot swap
# ---------------------------------------------------------------------------


class _FleetGen:
    """One immutable generation of the served fleet: the per-replica
    device copies plus per-tenant metadata (output conversion + the
    degrade path's host trees)."""

    __slots__ = ("fleets", "metas")

    def __init__(self, fleets: Tuple[PackedFleet, ...],
                 metas: Tuple[ModelMeta, ...]):
        self.fleets = fleets
        self.metas = metas

    @property
    def fleet(self) -> PackedFleet:
        return self.fleets[0]


class _Replica:
    """One dispatch replica: a device, a micro-batch queue, and an
    independent circuit breaker so degradation is per-replica."""

    __slots__ = ("index", "device", "queue", "worker", "breaker")

    def __init__(self, index: int, device, breaker: CircuitBreaker):
        self.index = index
        self.device = device
        self.queue: Queue = Queue()
        self.worker: Optional[threading.Thread] = None
        self.breaker = breaker


class FleetServer:
    """Thread-safe multi-tenant hot-swap predictor over a
    :class:`PackedFleet`, replicated across local devices.

    ``boosters`` seeds the M tenants (each a ``Booster``/``GBDT``/model
    path; seed a cold fleet by repeating one booster M times and
    ``swap_tenant``-ing later).  ``replicas`` picks how many local
    devices hold a fleet copy (0 = all local devices); request
    dispatch round-robins across them.  ``value_dtype="bf16"`` opts
    into the quantized leaf-value variant (routing exact, values
    ~3 decimal digits).  ``num_iteration``/``start_iteration`` select
    the served slice, applied on every swap, exactly like
    :class:`~.engine.PredictionServer`.
    """

    def __init__(self, boosters: Sequence, *, num_iteration: int = -1,
                 start_iteration: int = 0, min_bucket: int = 128,
                 replicas: int = 1, max_batch: int = 8192,
                 max_wait_ms: float = 2.0, host_fallback: bool = True,
                 value_dtype: str = "f32",
                 num_features: Optional[int] = None,
                 breaker_factory=None):
        from .. import compile_cache
        compile_cache.configure_from_env()
        if not boosters:
            raise LightGBMError("FleetServer needs at least one tenant")
        self.num_iteration = int(num_iteration)
        self.start_iteration = int(start_iteration)
        self.min_bucket = int(min_bucket)
        self.max_batch = int(max_batch)
        self.max_wait_ms = float(max_wait_ms)
        self.host_fallback = bool(host_fallback)
        self.value_dtype = str(value_dtype).lower()
        _value_dtype(self.value_dtype)   # validate early
        devices = jax.local_devices()
        n_rep = int(replicas) or len(devices)
        if n_rep < 1:
            raise LightGBMError(f"replicas must be >= 1, got {replicas}")
        # more replicas than devices: wrap around (separate queues and
        # breakers still isolate load/poison even on a shared chip)
        self._devices = [devices[i % len(devices)] for i in range(n_rep)]
        if breaker_factory is None:
            breaker_factory = lambda i: CircuitBreaker(  # noqa: E731
                failure_threshold=3, reprobe_interval_s=2.0)
        self._replicas = [_Replica(i, d, breaker_factory(i))
                          for i, d in enumerate(self._devices)]
        self._lock = threading.Lock()        # generation pointer
        self._swap_lock = threading.Lock()   # serializes swaps
        self._stopping = threading.Event()
        self._rr = 0

        gbdts = [_as_gbdt(b) for b in boosters]
        fleet, packs = pack_fleet(
            gbdts, num_features=num_features,
            start_iteration=self.start_iteration,
            num_iteration=self.num_iteration,
            value_dtype=self.value_dtype)
        metas = tuple(self._meta_for(g, pe)
                      for g, pe in zip(gbdts, packs))
        self._gen = _FleetGen(self._replicate(fleet), metas)
        obs.set_gauge("serve.fleet.tenants", fleet.num_tenants)
        obs.set_gauge("serve.fleet.replicas", n_rep)
        # anchor the rolling timeline at 0 dark replicas: without it a
        # first degradation mid-window would integrate as a full-window
        # outage in the SLO's dark fraction (obs/slo.py)
        obs.set_gauge("serve.fleet.degraded_replicas", 0)

    # -- construction helpers -------------------------------------------
    def _meta_for(self, gbdt, pe: PackedEnsemble) -> ModelMeta:
        host_trees = None
        if self.host_fallback:
            host_trees = list(tree_slice(
                gbdt.models, gbdt.num_model, self.start_iteration,
                self.num_iteration))
        return ModelMeta(gbdt, pe.num_iterations, host_trees,
                         pe.num_model)

    def _replicate(self, fleet: PackedFleet) -> Tuple[PackedFleet, ...]:
        return tuple(jax.device_put(fleet, d) for d in self._devices)

    # -- introspection --------------------------------------------------
    def _snapshot(self) -> _FleetGen:
        with self._lock:
            return self._gen

    @property
    def fleet(self) -> PackedFleet:
        return self._snapshot().fleet

    @property
    def num_tenants(self) -> int:
        return self._snapshot().fleet.num_tenants

    @property
    def num_replicas(self) -> int:
        return len(self._replicas)

    def degraded_replicas(self) -> List[int]:
        """Indices of replicas whose breaker is currently open."""
        return [r.index for r in self._replicas
                if r.breaker.state == "open"]

    def tenant(self, tenant_id: int) -> "TenantHandle":
        """A single-tenant view with the ``PredictionServer`` surface
        (``swap``/``predict``/``warmup``) — the pipeline's tenant-aware
        swap target (docs/Pipeline.md)."""
        return TenantHandle(self, tenant_id)

    # -- tenant hot swap ------------------------------------------------
    def swap_tenant(self, tenant_id: int, booster) -> bool:
        """Atomically replace ONE tenant.  Packing and the device index
        write happen outside the generation lock; readers only ever see
        complete generations.  Returns True when the new model fits the
        fleet's pad family — the zero-retrace index-write case; False
        means a pad grew and the whole fleet was re-padded (one-off
        retrace, like a solo swap that changes shape)."""
        m = int(tenant_id)
        gbdt = _as_gbdt(booster)
        with obs.span("serve.fleet.swap", cat="serve", tenant=m), \
                self._swap_lock:
            gen = self._snapshot()
            fl = gen.fleet
            if not 0 <= m < fl.num_tenants:
                raise LightGBMError(
                    f"tenant_id {m} out of range [0, {fl.num_tenants})")
            gbdt._flush_pending()
            pe = pack_ensemble(gbdt.models, gbdt.num_model,
                               start_iteration=self.start_iteration,
                               num_iteration=self.num_iteration,
                               num_features=fl.num_features)
            if pe.num_model != fl.num_model:
                raise LightGBMError(
                    f"tenant {m} booster has num_model={pe.num_model}; "
                    f"the fleet serves num_model={fl.num_model}")
            fits = fl.fits(pe)
            t_pad = max(fl.tree_pad, int(pe.split_feature.shape[0]))
            n_pad = max(fl.node_pad, int(pe.split_feature.shape[1]))
            w_pad = max(fl.word_pad, int(pe.cat_words.shape[0]))
            depth = max(fl.max_depth, int(pe.max_depth))
            dtype = _value_dtype(fl.value_dtype)
            row = PackedFleet(
                *(a[None] for a in _padded_tenant_arrays(
                    pe, t_pad, n_pad, w_pad, dtype)),
                num_tenants=1, num_model=fl.num_model, max_depth=depth,
                num_features=fl.num_features,
                value_dtype=fl.value_dtype)
            idx = np.int32(m)
            fleets = []
            for rep, cur in zip(self._replicas, gen.fleets):
                if not fits:
                    cur = self._grow_pads(cur, t_pad, n_pad, w_pad,
                                          depth)
                rrow = jax.device_put(row, rep.device)
                fleets.append(_fleet_write(cur, rrow, idx))
            metas = list(gen.metas)
            metas[m] = self._meta_for(gbdt, pe)
            # captured inside the serve.fleet.swap span: this tenant's
            # request spans link through the swap to the training
            # window above it (obs/tracing.py)
            metas[m].train_ctx = tracing.capture()
            new_gen = _FleetGen(tuple(fleets), tuple(metas))
            with self._lock:
                self._gen = new_gen
        obs.inc("serve.fleet.swaps")
        obs.inc(f"serve.fleet.tenant.{m}.swaps")
        if not fits:
            obs.inc("serve.fleet.swap_shape_changes")
        return fits

    @staticmethod
    def _grow_pads(fl: PackedFleet, t_pad: int, n_pad: int, w_pad: int,
                   depth: int) -> PackedFleet:
        """Re-pad every tenant of ``fl`` up to the new pad family (the
        shape-change swap path; a retrace follows by construction)."""
        dt = t_pad - fl.tree_pad
        dn = n_pad - fl.node_pad
        dw = w_pad - fl.word_pad

        def pad3(a, fill=0):
            return jnp.pad(a, ((0, 0), (0, dt), (0, dn)),
                           constant_values=fill)

        return PackedFleet(
            pad3(fl.split_feature), pad3(fl.threshold_hi),
            pad3(fl.threshold_lo), pad3(fl.decision_type),
            pad3(fl.left_child, -1), pad3(fl.right_child, -1),
            pad3(fl.cat_start), pad3(fl.cat_len),
            jnp.pad(fl.cat_words, ((0, 0), (0, dw))),
            jnp.pad(fl.leaf_value, ((0, 0), (0, dt), (0, dn))),
            jnp.pad(fl.is_stump, ((0, 0), (0, dt)),
                    constant_values=True),
            num_tenants=fl.num_tenants, num_model=fl.num_model,
            max_depth=depth, num_features=fl.num_features,
            value_dtype=fl.value_dtype)

    # -- warmup ---------------------------------------------------------
    def warmup(self, row_buckets: Optional[Sequence[int]] = None
               ) -> List[int]:
        """Precompile the fleet traversal for each pow2 row bucket on
        EVERY replica, plus the index-write program (so the first real
        ``swap_tenant`` is zero-retrace too).  ``None`` warms the
        standard small-batch ladder."""
        if row_buckets is None:
            row_buckets = [128, 1024, 8192]
        gen = self._snapshot()
        nf = gen.fleet.num_features
        done: List[int] = []
        for rows in row_buckets:
            b = row_bucket(int(rows), self.min_bucket)
            if b in done:
                continue
            with obs.span("serve.fleet.warmup", cat="serve", rows=b):
                zeros = np.zeros((b, nf))
                for rep, fl in zip(self._replicas, gen.fleets):
                    fleet_predict_scores(fl, 0, zeros, min_bucket=b)
            done.append(b)
        # identity re-write of tenant 0 compiles the swap program per
        # replica; the result is discarded, the generation is untouched
        for rep, fl in zip(self._replicas, gen.fleets):
            ch, aux = fl.tree_flatten()
            row = PackedFleet.tree_unflatten(
                (1,) + aux[1:], tuple(a[:1] for a in ch))
            _fleet_write(fl, row, np.int32(0))
        return done

    # -- prediction -----------------------------------------------------
    def _pick_replica(self) -> _Replica:
        with self._lock:
            i = self._rr
            self._rr = (i + 1) % len(self._replicas)
        return self._replicas[i]

    def _host_raw(self, gen: _FleetGen, tid: np.ndarray,
                  data: np.ndarray) -> np.ndarray:
        """(K, rows) float64 via each tenant's host tree walk — the
        per-replica degrade path (byte-identical to the tenant's
        ``Booster.predict`` raw accumulation)."""
        out = np.zeros((gen.fleet.num_model, data.shape[0]), np.float64)
        for m in np.unique(tid):
            meta = gen.metas[int(m)]
            if meta.host_trees is None:
                raise LightGBMError(
                    "fleet host fallback unavailable (host_fallback "
                    "was disabled)")
            rows = np.nonzero(tid == m)[0]
            out[:, rows] = meta.host_raw(data[rows])
        return out

    def _score_batch(self, rep: _Replica, gen: _FleetGen,
                     tid: np.ndarray, data: np.ndarray) -> np.ndarray:
        """(K, rows) raw scores on one replica with per-replica
        degradation: device kernel when the replica's breaker allows,
        the host tree walk when dispatch fails or the breaker is open.
        Input errors raise immediately and never count as device
        faults."""
        fl = gen.fleets[rep.index]
        if data.shape[1] < fl.num_features:
            # input fault: fails the request, never the availability
            # SLO (obs/slo.py) nor the breaker
            obs.inc("serve.fleet.input_errors")
            raise LightGBMError(
                f"query data has {data.shape[1]} features but the "
                f"fleet needs {fl.num_features}")
        err: Optional[BaseException] = None
        if rep.breaker.allow():
            try:
                faults.check("serve.fleet.dispatch")
                raw = fleet_predict_scores(fl, tid, data,
                                           min_bucket=self.min_bucket)
            except Exception as e:   # noqa: BLE001 — degrade, not drop
                err = e
            else:
                dark = rep.breaker.record_success()
                if dark is not None:
                    obs.observe("serve.fleet.degraded_time", dark)
                    self._record_degraded(rep, 0)
                    log_warning(
                        f"fleet replica {rep.index}: device path "
                        f"recovered after {dark:.3f} s degraded")
                obs.inc("serve.fleet.ok")
                return raw
        if not self.host_fallback:
            obs.inc("serve.fleet.failed")
            if err is not None:
                raise err
            raise LightGBMError(
                f"fleet replica {rep.index}: device path unavailable "
                f"(circuit open) and host fallback is disabled")
        out = self._host_raw(gen, tid, data)
        if err is not None:
            obs.inc("serve.fleet.device_failures")
            if rep.breaker.record_failure():
                self._record_degraded(rep, 1)
                log_warning(
                    f"fleet replica {rep.index}: device dispatch "
                    f"failing ({err!r}); circuit open — serving host "
                    f"fallback, re-probing every "
                    f"{rep.breaker.reprobe_interval_s:g} s")
        obs.inc("serve.fleet.fallback_requests")
        return out

    def _record_degraded(self, rep: _Replica, value: int) -> None:
        obs.set_gauge(f"serve.fleet.replica_degraded.{rep.index}", value)
        obs.set_gauge("serve.fleet.degraded_replicas",
                      len(self.degraded_replicas()))

    def _convert(self, gen: _FleetGen, tid: np.ndarray, raw: np.ndarray,
                 raw_score: bool) -> np.ndarray:
        """Per-tenant output conversion (objective / RF averaging) of a
        mixed batch: each tenant's rows get exactly what its solo
        server would return."""
        k = gen.fleet.num_model
        n = raw.shape[1]
        tenants = np.unique(tid)
        if len(tenants) == 1:
            return gen.metas[int(tenants[0])].convert(raw, raw_score)
        out = np.empty(n if k == 1 else (n, k), np.float64)
        for m in tenants:
            rows = np.nonzero(tid == m)[0]
            out[rows] = gen.metas[int(m)].convert(raw[:, rows],
                                                  raw_score)
        return out

    def predict(self, tenant_ids, data, raw_score: bool = False,
                replica: Optional[int] = None) -> np.ndarray:
        """Score a mixed-tenant batch — one device dispatch on one
        replica (round-robin unless ``replica`` pins it), each row
        answered exactly as its tenant's solo server would.  Output
        matches ``Booster.predict`` per row: (rows,) for single-model
        tenants, (rows, num_model) for multiclass."""
        data = np.atleast_2d(np.asarray(data, np.float64))
        n = int(data.shape[0])
        gen = self._snapshot()
        tid = np.asarray(tenant_ids, np.int32)
        if tid.ndim == 0:
            tid = np.full(n, int(tid), np.int32)
        # input faults, not device faults: fail the REQUEST before any
        # dispatch so neither the breaker nor the host fallback sees a
        # malformed batch (counted apart from availability, obs/slo.py)
        if tid.shape != (n,):
            obs.inc("serve.fleet.input_errors")
            raise LightGBMError(
                f"tenant_ids shape {tid.shape} does not match {n} rows")
        if n and (tid.min() < 0 or tid.max() >= gen.fleet.num_tenants):
            obs.inc("serve.fleet.input_errors")
            raise LightGBMError(
                f"tenant_ids must be in [0, {gen.fleet.num_tenants}); "
                f"got [{tid.min()}, {tid.max()}]")
        rep = (self._replicas[int(replica)] if replica is not None
               else self._pick_replica())
        with obs.span("serve.fleet.predict", cat="serve", rows=n,
                      replica=rep.index) as sp:
            if n and tracing.enabled() and int(tid.min()) == \
                    int(tid.max()):
                # single-tenant batch: link to the training window of
                # the one model generation answering it (mixed batches
                # have no single lineage to name)
                ctx = gen.metas[int(tid[0])].train_ctx
                if ctx is not None:
                    sp.set(tenant=int(tid[0]),
                           model_trace_id=ctx.trace_id,
                           model_span_id=ctx.span_id)
            raw = self._score_batch(rep, gen, tid, data)
            out = self._convert(gen, tid, raw, raw_score)
        obs.inc("serve.fleet.requests")
        obs.inc("serve.fleet.rows", n)
        if obs.enabled():
            for m, c in zip(*np.unique(tid, return_counts=True)):
                obs.inc(f"serve.fleet.tenant.{int(m)}.rows", int(c))
        return out

    # -- micro-batching across replicas ---------------------------------
    def start(self) -> "FleetServer":
        """Start one micro-batching worker per replica (idempotent)."""
        with self._lock:
            self._stopping.clear()
            for rep in self._replicas:
                if rep.worker is not None and rep.worker.is_alive():
                    continue
                rep.worker = threading.Thread(
                    target=self._drain_loop, args=(rep,),
                    name=f"lgbm-fleet-{rep.index}", daemon=True)
                rep.worker.start()
        return self

    def stop(self) -> None:
        with self._lock:
            workers = [rep.worker for rep in self._replicas]
            for rep in self._replicas:
                rep.worker = None
            # set the flag INSIDE the lock: submit() holds it across
            # its liveness check + enqueue, so every accepted request
            # is in a queue its worker will still drain before exiting
            self._stopping.set()
        for w in workers:
            if w is not None:
                w.join(timeout=10.0)

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    def submit(self, tenant_ids, data,
               raw_score: bool = False) -> Future:
        """Enqueue a (tenant_ids, rows) request on the next replica's
        micro-batch queue (round-robin); resolves to what ``predict``
        would return for those rows."""
        data = np.atleast_2d(np.asarray(data, np.float64))
        tid = np.asarray(tenant_ids, np.int32)
        if tid.ndim == 0:
            tid = np.full(data.shape[0], int(tid), np.int32)
        fut: Future = Future()
        rep = self._pick_replica()
        # liveness check + enqueue under the lock stop() sets
        # _stopping under: a request accepted here is guaranteed a
        # worker that drains its queue before exiting (no Future can
        # be orphaned by a concurrent stop())
        with self._lock:
            if (self._stopping.is_set() or rep.worker is None
                    or not rep.worker.is_alive()):
                raise LightGBMError("fleet micro-batching workers not "
                                    "running; call start() (or "
                                    "predict())")
            # the submitter's trace context rides the queue item to the
            # replica worker (None while tracing is off)
            rep.queue.put((tid, data, bool(raw_score), fut,
                           time.perf_counter(), tracing.capture()))
        obs.set_gauge(f"serve.fleet.replica_queue_depth.{rep.index}",
                      rep.queue.qsize())
        return fut

    def _drain_loop(self, rep: _Replica) -> None:
        while True:
            try:
                first = rep.queue.get(timeout=0.05)
            except Empty:
                if self._stopping.is_set():
                    return
                continue
            batch = [first]
            rows = first[1].shape[0]
            deadline = time.perf_counter() + self.max_wait_ms / 1e3
            while rows < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    item = rep.queue.get(timeout=remaining)
                except Empty:
                    break
                batch.append(item)
                rows += item[1].shape[0]
            obs.set_gauge(f"serve.fleet.replica_queue_depth.{rep.index}",
                          rep.queue.qsize())
            self._run_batch(rep, batch)

    def _run_batch(self, rep: _Replica, batch: List[Tuple]) -> None:
        now = time.perf_counter()
        for _, _, _, _, t0, _ in batch:
            obs.observe("serve.fleet.queue_wait", now - t0)
        for flavor in sorted({rs for _, _, rs, _, _, _ in batch}):
            group = [b for b in batch if b[2] == flavor]
            try:
                if len(group) > 1:
                    tid = np.concatenate([g[0] for g in group])
                    data = np.concatenate([g[1] for g in group], axis=0)
                else:
                    tid, data = group[0][0], group[0][1]
                out = self.predict(tid, data, raw_score=flavor,
                                   replica=rep.index)
            except Exception:   # noqa: BLE001 — isolate the poison
                # one poisoned submit fails only its OWN Future
                # (docs/Robustness.md): retry each request alone
                obs.inc("serve.fleet.poisoned_batches")
                for g in group:
                    try:
                        res = self.predict(g[0], g[1], raw_score=flavor,
                                           replica=rep.index)
                    except Exception as e:   # noqa: BLE001
                        if not g[3].done():
                            g[3].set_exception(e)
                    else:
                        if not g[3].done():
                            g[3].set_result(res)
                continue
            lo = 0
            for g in group:
                hi = lo + g[1].shape[0]
                if not g[3].done():
                    g[3].set_result(out[lo:hi])
                lo = hi
        done = time.perf_counter()
        for _, data, _, fut, t0, ctx in batch:
            if (fut.done() and not fut.cancelled()
                    and fut.exception() is None):
                obs.observe("serve.fleet.request_latency", done - t0)
                if ctx is not None:
                    # submit -> replica flush causal edge, parented
                    # under the submitter's active span
                    obs.span_event(
                        "serve.fleet.request", t0, done - t0,
                        cat="serve", rows=int(data.shape[0]),
                        replica=rep.index,
                        span_id=tracing.new_id(),
                        trace_id=ctx.trace_id,
                        **({"parent_id": ctx.span_id}
                           if ctx.span_id else {}))


class TenantHandle:
    """One tenant of a :class:`FleetServer` behind the solo
    ``PredictionServer`` surface (``swap``/``predict``/``warmup``/
    ``_model``), so the retrain pipeline — or any other solo-server
    client — can target a fleet tenant without knowing about fleets."""

    __slots__ = ("fleet_server", "tenant_id")

    def __init__(self, fleet_server: FleetServer, tenant_id: int):
        m = int(tenant_id)
        if not 0 <= m < fleet_server.num_tenants:
            raise LightGBMError(
                f"tenant_id {m} out of range "
                f"[0, {fleet_server.num_tenants})")
        self.fleet_server = fleet_server
        self.tenant_id = m

    @property
    def _model(self) -> Optional[ModelMeta]:
        return self.fleet_server._snapshot().metas[self.tenant_id]

    def swap(self, booster) -> bool:
        return self.fleet_server.swap_tenant(self.tenant_id, booster)

    def predict(self, data, raw_score: bool = False) -> np.ndarray:
        return self.fleet_server.predict(self.tenant_id, data,
                                         raw_score=raw_score)

    def warmup(self, row_buckets: Optional[Sequence[int]] = None
               ) -> List[int]:
        return self.fleet_server.warmup(row_buckets)

    def stop(self) -> None:
        """No-op: the fleet's replicas outlive any one tenant view."""
