"""Hot-swap prediction server over the packed-forest device kernel.

The fork's serving shape (PAPER.md, ``src/test.cpp``): a window loop
retrains a fresh booster every N requests while EVERY arriving request
is scored against the current model.  :class:`PredictionServer` owns
that read side:

* ``swap(booster)`` atomically replaces the packed ensemble — the
  expensive part (flatten + device upload) happens before the lock, so
  in-flight ``predict`` calls never observe a half-built model, and a
  swap whose pad signature matches the previous model re-dispatches
  into the already-compiled programs (ZERO retraces — the window loop's
  steady state);
* ``predict(rows)`` pads the batch to a pow2 row bucket and runs the
  whole ensemble in one device dispatch;
* optional micro-batching (``start()``/``submit(rows)``): tiny
  per-request batches coalesce up to ``max_batch`` rows or
  ``max_wait_ms``, amortizing dispatch overhead under concurrent
  callers;
* ``warmup(...)`` precompiles the configured row buckets so the first
  real request never pays a trace+compile;
* **graceful degradation** (docs/Robustness.md): when the device
  dispatch fails (preemption, runtime death — or the ``serve.dispatch``
  injected fault), the batch is answered by the HOST ``Tree.predict``
  walk over the same served tree slice (float64, byte-identical to
  ``Booster.predict``'s host path), a circuit breaker trips after
  ``failure_threshold`` consecutive device failures so later requests
  skip the dead device entirely, and a timed re-probe recovers to the
  device path once it heals — injected device death drops ZERO
  requests.

Telemetry (all under the ``serve.`` prefix, see docs/Observability.md):
``serve.predict`` / ``serve.queue_wait`` / ``serve.request_latency``
timings (p50/p95 come from the registry), ``serve.batch_rows`` gauge,
``serve.swaps`` / ``serve.requests`` / ``serve.rows`` /
``serve.device_batches`` counters; degradation adds the
``serve.degraded`` gauge (1 while the breaker is open),
``serve.device_failures`` / ``serve.fallback_requests`` counters and
the ``serve.degraded_time`` timing (seconds per dark period).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from queue import Empty, Queue
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..obs import tracing
from ..robust import faults
from ..robust.retry import CircuitBreaker
from ..utils.log import LightGBMError, log_warning
from .packed import (PackedEnsemble, pack_gbdt, predict_scores,
                     row_bucket, tree_slice)

__all__ = ["PredictionServer", "warmup_bucket_ladder"]


def warmup_bucket_ladder(min_rows: Optional[int] = None,
                         min_bucket: int = 128) -> List[int]:
    """The ONE definition of the default warmup bucket set: the
    small-batch ladder plus the ``device_predict_min_rows`` bucket —
    the batch size at which ``GBDT.predict_raw`` auto-routing switches
    to the device kernel, so the first large batch is never a cold
    compile.  Shared by :meth:`PredictionServer.default_warmup_buckets`
    and the AOT serving warmup (``lightgbm_tpu.warmup.warmup_serve``);
    ``None`` means the schema default."""
    if min_rows is None:
        from ..params import PARAM_BY_NAME
        min_rows = int(PARAM_BY_NAME["device_predict_min_rows"].default)
    out = [128, 1024, 8192]
    if min_rows > 0:
        b = row_bucket(int(min_rows), min_bucket)
        if b not in out:
            out.append(b)
    return out


def _as_gbdt(booster):
    """Accept a ``basic.Booster``, a raw ``GBDT`` (trained or
    file-loaded), or a model-file path."""
    if isinstance(booster, str):
        from ..boosting.gbdt import GBDT
        return GBDT.load_model_from_file(booster)
    return getattr(booster, "_gbdt", booster)


class ModelMeta:
    """The booster-level facts of one served model generation that are
    independent of WHERE its packed tables live (a solo
    :class:`~.packed.PackedEnsemble` or one tenant row of a
    :class:`~.fleet.PackedFleet`): the output conversion
    ``Booster.predict`` would apply, and (for the degrade path) the
    host ``Tree`` objects of the SAME served slice so a dead device
    never drops a request."""

    __slots__ = ("objective", "objective_str", "average_output",
                 "n_iters", "host_trees", "num_model", "train_ctx")

    def __init__(self, gbdt, n_iters: int, host_trees=None,
                 num_model: int = 1):
        self.objective = gbdt.objective
        self.objective_str = gbdt.loaded_objective_str
        self.average_output = bool(gbdt.average_output)
        self.n_iters = int(n_iters)
        self.host_trees = host_trees
        self.num_model = max(int(num_model), 1)
        # trace context captured at swap time (obs/tracing.py): when the
        # swap ran under a pipeline window, every predict span answered
        # by this generation links back to the window that trained it
        self.train_ctx = None

    def host_raw(self, data: np.ndarray) -> np.ndarray:
        """(K, rows) float64 raw scores via the host tree walk — the
        exact accumulation ``GBDT.predict_raw``'s host path performs
        over this slice, so fallback answers match ``Booster.predict``
        byte for byte.  Trees interleave iteration-major
        (``out[i % num_model]``), the same order ``pack_ensemble``
        lays the packed tree axis out in."""
        out = np.zeros((self.num_model, data.shape[0]), np.float64)
        for i, tree in enumerate(self.host_trees):
            out[i % self.num_model] += tree.predict(data)
        return out

    def convert(self, raw: np.ndarray, raw_score: bool) -> np.ndarray:
        """(K, R) raw -> user-facing values, matching GBDT.predict."""
        if self.average_output:
            if self.n_iters > 0:
                raw = raw / self.n_iters
        elif not raw_score:
            if self.objective is not None:
                raw = self.objective.convert_output(raw)
            elif self.objective_str:
                from ..boosting.gbdt import _convert_by_name
                raw = _convert_by_name(self.objective_str, raw)
        if raw.shape[0] == 1:
            return raw[0]
        return raw.T


class _Model(ModelMeta):
    """One immutable generation of the solo server's model: the packed
    ensemble plus its :class:`ModelMeta`."""

    __slots__ = ("packed",)

    def __init__(self, packed: PackedEnsemble, gbdt, host_trees=None):
        super().__init__(gbdt, packed.num_iterations, host_trees,
                         packed.num_model)
        self.packed = packed


class PredictionServer:
    """Thread-safe hot-swap predictor over a :class:`PackedEnsemble`.

    ``booster`` may be a ``Booster``, a ``GBDT``, or a model-file path;
    pass ``None`` to create an empty server and ``swap()`` later.
    ``num_iteration``/``start_iteration`` select the served tree slice
    (applied on every swap).  ``max_batch``/``max_wait_ms`` configure
    the optional micro-batching queue (``start()``/``submit()``).

    ``host_fallback`` (default on) keeps the served slice's host trees
    so device-dispatch failures degrade to the host walk instead of
    dropping requests; ``breaker`` overrides the default circuit
    breaker (3 consecutive failures trip it, re-probe every 2 s).
    """

    def __init__(self, booster=None, *, num_iteration: int = -1,
                 start_iteration: int = 0, max_batch: int = 8192,
                 max_wait_ms: float = 2.0, min_bucket: int = 128,
                 device_predict_min_rows: Optional[int] = None,
                 host_fallback: bool = True,
                 breaker: Optional[CircuitBreaker] = None):
        # serving restarts cold too: pick up the persistent compile
        # cache from the environment so the packed traversal programs
        # load from disk (docs/ColdStart.md)
        from .. import compile_cache
        compile_cache.configure_from_env()
        self._lock = threading.Lock()
        self._model: Optional[_Model] = None
        self.num_iteration = int(num_iteration)
        self.start_iteration = int(start_iteration)
        self.max_batch = int(max_batch)
        self.max_wait_ms = float(max_wait_ms)
        self.min_bucket = int(min_bucket)
        # warmup() default buckets derive from this (None = adopt the
        # swapped booster's config, else the schema default): the bucket
        # the GBDT.predict_raw auto-routing switches to the device
        # kernel at MUST be warm, or the first large batch pays the
        # cold compile the small-bucket warmups were meant to prevent
        self.device_predict_min_rows = (
            None if device_predict_min_rows is None
            else int(device_predict_min_rows))
        self.host_fallback = bool(host_fallback)
        self._breaker = breaker if breaker is not None else \
            CircuitBreaker(failure_threshold=3, reprobe_interval_s=2.0)
        self._queue: Queue = Queue()
        self._worker: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        if booster is not None:
            self.swap(booster)

    @property
    def degraded(self) -> bool:
        """True while the circuit breaker is open (device path dark,
        requests answered by the host fallback)."""
        return self._breaker.state == "open"

    @property
    def dark_seconds(self) -> float:
        """Total breaker-open seconds, including a still-open period —
        the live availability denominator the SLO engine charges
        against (``serve.degraded_time`` only lands at recovery)."""
        return self._breaker.dark_seconds()

    # -- model lifecycle ------------------------------------------------
    def swap(self, booster) -> bool:
        """Atomically replace the served model.  Packing and device
        upload happen OUTSIDE the lock; readers switch between complete
        generations only.  Returns True when the new model's pad
        signature matches the previous one — the zero-retrace case the
        window loop relies on."""
        gbdt = _as_gbdt(booster)
        if self.device_predict_min_rows is None:
            cfg_rows = getattr(getattr(gbdt, "config", None),
                               "device_predict_min_rows", None)
            if cfg_rows is not None:
                self.device_predict_min_rows = int(cfg_rows)
        with obs.span("serve.swap", cat="serve"):
            packed = pack_gbdt(gbdt, self.start_iteration,
                               self.num_iteration)
            host_trees = None
            if self.host_fallback:
                # the host trees of the SAME slice pack_gbdt flattened
                # (shared clamping in packed.tree_slice) — the degrade
                # path's answers must cover exactly the served trees
                host_trees = list(tree_slice(
                    gbdt.models, gbdt.num_model, self.start_iteration,
                    self.num_iteration))
            model = _Model(packed, gbdt, host_trees)
            # captured inside the serve.swap span: request spans link
            # through the swap to the training window above it
            model.train_ctx = tracing.capture()
            with self._lock:
                prev = self._model
                self._model = model
        same_shape = (prev is not None and
                      prev.packed.shape_signature()
                      == packed.shape_signature())
        obs.inc("serve.swaps")
        if prev is not None and not same_shape:
            obs.inc("serve.swap_shape_changes")
        return same_shape

    def _snapshot(self) -> _Model:
        with self._lock:
            model = self._model
        if model is None:
            raise LightGBMError("PredictionServer has no model; call "
                                "swap(booster) first")
        return model

    @property
    def packed(self) -> PackedEnsemble:
        return self._snapshot().packed

    def default_warmup_buckets(self) -> List[int]:
        """The bucket ladder ``warmup()`` precompiles by default
        (:func:`warmup_bucket_ladder` with this server's configured
        ``device_predict_min_rows``)."""
        return warmup_bucket_ladder(self.device_predict_min_rows,
                                    self.min_bucket)

    def warmup(self, row_buckets: Optional[Sequence[int]] = None
               ) -> List[int]:
        """Precompile the traversal program for each pow2 row bucket;
        returns the bucket list actually compiled.  Idempotent: warm
        buckets hit the jit cache.  ``None`` uses
        :meth:`default_warmup_buckets` (which includes the
        ``device_predict_min_rows`` bucket)."""
        if row_buckets is None:
            row_buckets = self.default_warmup_buckets()
        model = self._snapshot()
        nf = model.packed.num_features
        done = []
        for rows in row_buckets:
            b = row_bucket(int(rows), self.min_bucket)
            if b in done:
                continue
            with obs.span("serve.warmup", cat="serve", rows=b):
                predict_scores(model.packed, np.zeros((b, nf)),
                               min_bucket=self.min_bucket)
            done.append(b)
        return done

    # -- direct prediction ----------------------------------------------
    def _score_batch(self, model: _Model, data: np.ndarray) -> np.ndarray:
        """(K, rows) raw scores with graceful degradation: the device
        kernel when the circuit breaker allows it, the host tree walk
        when dispatch fails or the breaker is open.  Input errors (too
        few features) raise immediately and never count against the
        device."""
        if data.shape[1] < model.packed.num_features:
            # an input fault, not a device fault — fail the REQUEST
            # without involving breaker or fallback (the host walk would
            # read out-of-range feature indices).  Distinguished in
            # telemetry: input errors never count against availability
            # (obs/slo.py)
            obs.inc("serve.input_errors")
            raise LightGBMError(
                f"query data has {data.shape[1]} features but the "
                f"served model needs {model.packed.num_features}")
        err: Optional[BaseException] = None
        if self._breaker.allow():
            try:
                faults.check("serve.dispatch")
                raw = predict_scores(model.packed, data,
                                     min_bucket=self.min_bucket)
            except Exception as e:   # noqa: BLE001 — degrade, not drop
                err = e
            else:
                dark = self._breaker.record_success()
                if dark is not None:
                    obs.observe("serve.degraded_time", dark)
                    log_warning(f"serve: device path recovered after "
                                f"{dark:.3f} s degraded")
                # written on EVERY success, not just recovery: the
                # rolling gauge timeline integrates from its first
                # transition, so the healthy prefix must be on record
                # or a trip late in a window reads as a full-window
                # outage (a same-value re-set is a no-op in the ring)
                obs.set_gauge("serve.degraded", 0)
                obs.inc("serve.ok")
                return raw
        if not self.host_fallback or model.host_trees is None:
            # the request goes UNANSWERED: the availability SLO's hard
            # failure bucket
            obs.inc("serve.failed")
            if err is not None:
                raise err
            raise LightGBMError(
                "serve: device path unavailable (circuit open) and "
                "host fallback is disabled")
        out = model.host_raw(data)
        # the host walk answered, so the device exception above was a
        # DEVICE fault (not an input fault): count it toward the breaker
        if err is not None:
            obs.inc("serve.device_failures")
            if self._breaker.record_failure():
                obs.set_gauge("serve.degraded", 1)
                log_warning(f"serve: device dispatch failing ({err!r}); "
                            f"circuit open — serving host fallback, "
                            f"re-probing every "
                            f"{self._breaker.reprobe_interval_s:g} s")
        obs.inc("serve.fallback_requests")
        return out

    def predict(self, data, raw_score: bool = False) -> np.ndarray:
        """Score a raw feature matrix against the current model — one
        device dispatch, row-padded to a pow2 bucket (host-walk
        fallback under device failure, see :meth:`_score_batch`).
        Output matches ``Booster.predict``: (rows,) for single-model
        ensembles, (rows, num_model) for multiclass."""
        data = np.atleast_2d(np.asarray(data, np.float64))
        model = self._snapshot()
        with obs.span("serve.predict", cat="serve",
                      rows=int(data.shape[0])) as sp:
            ctx = model.train_ctx
            if ctx is not None:
                # cross-chain link (not a parent edge): the model that
                # answers this request, back to its training window
                sp.set(model_trace_id=ctx.trace_id,
                       model_span_id=ctx.span_id)
            obs.set_gauge("serve.batch_rows", int(data.shape[0]))
            raw = self._score_batch(model, data)
            out = model.convert(raw, raw_score)
        obs.inc("serve.requests")
        obs.inc("serve.rows", int(data.shape[0]))
        return out

    # -- micro-batching queue -------------------------------------------
    def start(self) -> "PredictionServer":
        """Start the micro-batching worker thread (idempotent)."""
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return self
            self._stopping.clear()
            self._worker = threading.Thread(
                target=self._drain_loop, name="lgbm-serve", daemon=True)
            self._worker.start()
        return self

    def stop(self) -> None:
        """Stop the worker; queued requests are drained first."""
        with self._lock:
            worker = self._worker
            self._worker = None
            # set the flag INSIDE the lock: submit() holds it across
            # its liveness check + enqueue, so a request accepted
            # concurrently with stop() still lands in a queue the
            # worker drains before exiting
            self._stopping.set()
        if worker is None:
            return
        worker.join(timeout=10.0)

    def __enter__(self) -> "PredictionServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    def submit(self, data, raw_score: bool = False) -> Future:
        """Enqueue rows for micro-batched prediction; resolves to the
        same values ``predict`` would return for those rows."""
        fut: Future = Future()
        data = np.atleast_2d(np.asarray(data, np.float64))
        with self._lock:
            if (self._stopping.is_set() or self._worker is None
                    or not self._worker.is_alive()):
                raise LightGBMError("micro-batching worker not running; "
                                    "call start() (or use predict())")
            # the submitter's trace context rides the queue item (None
            # while tracing is off): the worker's flush emits a
            # serve.request span parented under the submit site
            self._queue.put((data, bool(raw_score), fut,
                             time.perf_counter(), tracing.capture()))
        return fut

    def _drain_loop(self) -> None:
        while True:
            try:
                first = self._queue.get(timeout=0.05)
            except Empty:
                if self._stopping.is_set():
                    return
                continue
            batch = [first]
            rows = first[0].shape[0]
            deadline = time.perf_counter() + self.max_wait_ms / 1e3
            while rows < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    item = self._queue.get(timeout=remaining)
                except Empty:
                    break
                batch.append(item)
                rows += item[0].shape[0]
            self._run_batch(batch)

    def _run_batch(self, batch: List[Tuple]) -> None:
        now = time.perf_counter()
        for _, _, _, t0, _ in batch:
            obs.observe("serve.queue_wait", now - t0)
        # one dispatch per raw_score flavor present in the batch
        for flavor in sorted({rs for _, rs, _, _, _ in batch}):
            group = [b for b in batch if b[1] == flavor]
            try:
                data = np.concatenate([g[0] for g in group], axis=0) \
                    if len(group) > 1 else group[0][0]
                out = self.predict(data, raw_score=flavor)
            except Exception:   # noqa: BLE001 — isolate the poison
                # fault isolation (docs/Robustness.md): one poisoned
                # submit must fail only its OWN Future — retry each
                # request alone so the good ones still resolve and the
                # worker keeps draining later batches
                obs.inc("serve.poisoned_batches")
                for g in group:
                    try:
                        res = self.predict(g[0], raw_score=flavor)
                    except Exception as e:   # noqa: BLE001 — per-future
                        if not g[2].done():
                            g[2].set_exception(e)
                    else:
                        if not g[2].done():
                            g[2].set_result(res)
                continue
            lo = 0
            for g in group:
                hi = lo + g[0].shape[0]
                # a caller may have cancelled its Future (result
                # timeout); resolving it would raise InvalidStateError
                # and kill the worker thread
                if not g[2].done():
                    g[2].set_result(out[lo:hi])
                lo = hi
        done = time.perf_counter()
        for data, _, fut, t0, ctx in batch:
            if (fut.done() and not fut.cancelled()
                    and fut.exception() is None):
                obs.observe("serve.request_latency", done - t0)
                if ctx is not None:
                    # submit -> flush causal edge: one span per request
                    # spanning submit time to future resolution,
                    # parented under the submitter's active span
                    obs.span_event(
                        "serve.request", t0, done - t0, cat="serve",
                        rows=int(data.shape[0]),
                        span_id=tracing.new_id(),
                        trace_id=ctx.trace_id,
                        **({"parent_id": ctx.span_id}
                           if ctx.span_id else {}))
