"""Persistent XLA compile cache: zero *recompiles* across processes.

BENCH_r05 measured ``warmup_compile_s`` = 239.4 s against 225.5 s of
timed training — every fresh process pays a full training-run's worth of
XLA compilation, which is disqualifying for the fork's
retrain-every-window production story (the harness retrains through the
C API every window, and deployments restart).  PR 4's ``GrowerPrograms``
cache already gives zero *retraces* within a process; this module closes
the cross-process half by activating JAX's persistent compilation cache
(``jax_compilation_cache_dir``) as a first-class, library-level
subsystem instead of a bench.py-only env default:

* ``configure(cache_dir)`` — point JAX at an on-disk LRU cache of
  compiled executables.  Every entry point calls
  :func:`configure_from_config` / :func:`configure_from_env`
  (``GBDT.init_train``, the CLI, ``capi_embed`` import,
  ``PredictionServer``, ``bench.py``, ``examples/cache_admission.py``),
  so exporting ``LGBM_TPU_COMPILE_CACHE=/path`` warms ANY driver with no
  code change;
* the min-compile-time floor is forced to 0 while active: the whole
  point is a warm cold start, and JAX's default 1 s floor would leave
  the eager glue ops (score scatter, boost-from-average add, ...) cold —
  exactly the entries the CI smoke's zero-miss gate
  (``scripts/check_coldstart.py``) pins;
* hit/miss telemetry: JAX emits ``/jax/compilation_cache/*`` monitoring
  events at every compile; :func:`install_listeners` maps them onto obs
  counters (``compile_cache.hits`` / ``misses`` / ``requests`` and the
  ``compile_cache.time_saved`` timing) next to the per-signature retrace
  tracking in ``obs/jit_track.py``, so a run's metrics snapshot shows
  BOTH layers of the caching story (docs/Observability.md);
* knobs: ``compile_cache_min_entry_bytes`` (skip tiny entries when a
  deployment wants a lean cache dir) and ``compile_cache_strict_keys``
  (include compiler/runtime build metadata in the cache key — the
  sharing-safety switch for a cache dir mounted across heterogeneous
  hosts; false hits are impossible either way on identical builds, the
  strict mode just refuses cross-build reuse instead of trusting the
  serialized executable's compatibility).

The cache key is XLA's (HLO module + compile options + backend), NOT
lightgbm_tpu's ``programs_signature`` — so a warmup run only has to
reproduce the *traced program* (shapes, num_leaves, max_bin, chunk,
stage plan), not the exact data or regularization values (those are
traced arguments).  docs/ColdStart.md lists which parameters shape
traces.

Everything imports ``jax`` lazily: importing this module costs nothing
and is safe before backend selection.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from . import obs

ENV_VAR = "LGBM_TPU_COMPILE_CACHE"
_FALSY = ("", "0", "false", "no", "off")

# guarded module state (configure may race between a PredictionServer
# thread and the training driver)
_LOCK = threading.Lock()
_STATE = {"dir": None, "listeners": False}

# own always-on counters (compiles are rare; the lock is uncontended):
# warmup reports and the CI zero-miss smoke must not depend on the obs
# registry being enabled.  Mirrored into obs when telemetry is on.
_COUNTS = {"hits": 0, "misses": 0, "requests": 0,
           "backend_compile_s": 0.0, "time_saved_s": 0.0}

_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "hits",
    "/jax/compilation_cache/cache_misses": "misses",
    "/jax/compilation_cache/compile_requests_use_cache": "requests",
}

# actual XLA backend-compile seconds this process paid: a persistent-
# cache hit skips this entirely, so cold/warm runs of the same shapes
# differ by exactly this component (tracing is Python work the disk
# cache cannot remove — on CPU backends it dominates the residual, so
# the coldstart test gates on THIS ratio while the TPU bench gates the
# wall-clock one)
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _on_event(event, **kwargs) -> None:
    key = _EVENT_COUNTERS.get(event)
    if key is not None:
        with _LOCK:
            _COUNTS[key] += 1
        obs.inc(f"compile_cache.{key}")


def _on_duration(event, duration, **kwargs) -> None:
    if event == _BACKEND_COMPILE_EVENT:
        with _LOCK:
            _COUNTS["backend_compile_s"] += float(duration)
        obs.observe("compile_cache.backend_compile", float(duration))
    elif event == "/jax/compilation_cache/compile_time_saved_sec":
        # JAX reports saved = original_compile - retrieval; for sub-ms
        # executables retrieval can exceed the compile, making this
        # negative — clamp so the timing histogram keeps its
        # total >= max invariant (the net saving of such entries is ~0)
        saved = max(float(duration), 0.0)
        with _LOCK:
            _COUNTS["time_saved_s"] += saved
        obs.observe("compile_cache.time_saved", saved)


def install_listeners() -> None:
    """Register the JAX monitoring listeners (idempotent).  The
    listeners themselves are two dict lookups per compile and feed the
    obs registry only while telemetry is enabled."""
    with _LOCK:
        if _STATE["listeners"]:
            return
        _STATE["listeners"] = True
    import jax

    jax.monitoring.register_event_listener(_on_event)
    jax.monitoring.register_event_duration_secs_listener(_on_duration)


def cache_dir() -> Optional[str]:
    """The directory this module last activated, or None."""
    return _STATE["dir"]


def artifact_dir(name: str) -> Optional[str]:
    """Directory for small library artifacts persisted beside the
    compiled executables (e.g. ``stage_plans`` — profiled wave-stage
    plans, ops/stage_plan.py) so they share the compile cache's
    lifecycle: warm a deployment's cache dir and its profiled plans
    travel with it.  Not created here; None when no cache is active."""
    d = cache_dir()
    if not d:
        return None
    return os.path.join(d, name)


def configure(cache_dir: Optional[str], *,
              min_entry_bytes: Optional[int] = None,
              strict_keys: Optional[bool] = None,
              _pin: bool = True) -> Optional[str]:
    """Activate the persistent compilation cache at ``cache_dir``.

    Returns the expanded directory (created if missing), or None when
    ``cache_dir`` is falsy ("", "0", "false", "off" all mean "leave the
    cache alone" — an env var that disabled it stays disabled).  The
    compile-seconds/hit/miss listeners install either way, so
    :func:`counters` works even without a cache dir.

    ``min_entry_bytes`` / ``strict_keys`` are STICKY: ``None`` keeps
    whatever an earlier configure set (first activation applies the
    schema defaults 0 / False) — a knob explicitly set through params
    must survive the env-only reconfigures every entry point performs
    (``PredictionServer``, the ``capi_embed`` import, later windows).

    Re-configuring with the SAME directory is a cheap no-op; switching
    directories mid-process resets JAX's internal cache object so later
    compiles read/write the new location (JAX memoizes the cache handle
    at first compile).
    """
    install_listeners()
    if cache_dir is None or str(cache_dir).strip().lower() in _FALSY:
        return None
    path = os.path.abspath(os.path.expanduser(str(cache_dir)))
    import jax

    os.makedirs(path, exist_ok=True)   # before any state change: may raise
    with _LOCK:
        changed = _STATE["dir"] != path
        _STATE["dir"] = path
        if _pin:
            # every EXPLICIT activation (param, library call, CLI flag)
            # pins the dir against later env-only reconfigures; only
            # the env path itself activates unpinned
            _STATE["pinned"] = True
        if min_entry_bytes is not None:
            _STATE["min_entry_bytes"] = int(min_entry_bytes)
        if strict_keys is not None:
            _STATE["strict_keys"] = bool(strict_keys)
        entry_floor = _STATE.get("min_entry_bytes", 0)
        strict = _STATE.get("strict_keys", False)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_enable_compilation_cache", True)
    # floor = 0: the warm-cold-start contract needs EVERY executable the
    # training run dispatches persisted, including sub-second glue ops
    # (the CI smoke asserts zero misses after an AOT warmup)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                      entry_floor)
    jax.config.update("jax_compilation_cache_include_metadata_in_key",
                      strict)
    if changed:
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:   # pragma: no cover — private API moved
            pass
    return path


def configure_from_env() -> Optional[str]:
    """Activate from ``LGBM_TPU_COMPILE_CACHE`` (no-op when unset or
    falsy) — how the native ``liblgbm_tpu`` harness and the
    ``PredictionServer`` pick the cache up without a config object.

    A dir explicitly configured (param, library call, CLI flag) wins:
    once any pinned :func:`configure` activated a directory, this call
    leaves it alone (otherwise creating a PredictionServer mid-training
    would flip the process-wide cache back to the env dir and abandon
    the warm entries).  Never raises: a bad env path (read-only FS,
    permission) must not take down training/serving over a cache — it
    logs a warning and degrades to no persistent cache."""
    with _LOCK:
        current = _STATE["dir"] if (_STATE["dir"]
                                    and _STATE.get("pinned")) else None
    if current:
        install_listeners()
        return current
    try:
        return configure(os.environ.get(ENV_VAR, ""), _pin=False)
    except OSError as e:
        from .utils.log import log_warning
        log_warning(f"cannot activate the persistent compile cache from "
                    f"{ENV_VAR}: {e}; continuing without it")
        return None


def configure_from_config(cfg) -> Optional[str]:
    """Activate from a :class:`~lightgbm_tpu.config.Config`.

    ``compile_cache_dir`` wins when set; otherwise the env var decides
    the DIR while the config's knobs still apply (sticky — see
    :func:`configure`).  Called on every ``GBDT.init_train`` — once per
    retrain window — so it must stay cheap (same-dir reconfigure is a
    string compare).
    """
    path = str(getattr(cfg, "compile_cache_dir", "") or "")
    # schema defaults (0 / False) equal the sticky initial values, so a
    # default-valued config passes None = "keep what's set" — only a
    # non-default knob overrides (and sticks for the process)
    raw_entry = int(getattr(cfg, "compile_cache_min_entry_bytes", 0) or 0)
    knobs = dict(
        min_entry_bytes=raw_entry if raw_entry else None,
        strict_keys=True if getattr(cfg, "compile_cache_strict_keys",
                                    False) else None)
    if not path:
        path = os.environ.get(ENV_VAR, "")
        if not path or str(path).strip().lower() in _FALSY:
            install_listeners()
            return None
        try:
            # dir came from the env: activate unpinned, so a later
            # explicit dir can still take over
            return configure(path, _pin=False, **knobs)
        except OSError as e:
            from .utils.log import log_warning
            log_warning(f"cannot activate the persistent compile cache "
                        f"from {ENV_VAR}: {e}; continuing without it")
            return None
    return configure(path, **knobs)


def counters() -> dict:
    """Process-lifetime persistent-cache hit/miss/request counts
    (independent of the obs registry, which mirrors them as
    ``compile_cache.*`` counters while telemetry is enabled)."""
    with _LOCK:
        return dict(_COUNTS)
