"""lightgbm_tpu: a TPU-native gradient-boosted decision tree framework.

Brand-new implementation of the LightGBM v2.2.2 capability surface
(histogram-based leaf-wise GBDT, GOSS/DART/RF, EFB, categorical optimal
splits, monotone constraints, full objective/metric set, feature/data/voting
parallel distributed training) designed for TPU: the binned feature matrix is
HBM-resident, histogram construction and split scanning run as Pallas/XLA
kernels, and distributed modes use jax.lax collectives over a device mesh.
"""

from .config import Config
from .utils.log import LightGBMError, register_log_callback, set_verbosity

__version__ = "0.1.0"

# public API filled in as layers land; basic/engine/sklearn imported lazily to
# keep `import lightgbm_tpu` light before jax initialisation is needed
__all__ = [
    "Config", "LightGBMError", "register_log_callback", "set_verbosity",
    "Dataset", "Booster", "train", "cv",
    "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
    "PredictionServer",
]


def __getattr__(name):
    if name in ("Dataset", "Booster"):
        from . import basic
        return getattr(basic, name)
    if name == "PredictionServer":
        from .serve import PredictionServer
        return PredictionServer
    if name in ("train", "cv"):
        from . import engine
        return getattr(engine, name)
    if name in ("LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"):
        from . import sklearn
        return getattr(sklearn, name)
    raise AttributeError(f"module 'lightgbm_tpu' has no attribute {name}")
