"""Ahead-of-time warmup: precompile a deployment's program families.

The retrain-every-window harness restarts; BENCH_r05 showed a fresh
process paying 239 s of XLA compilation before its first trained tree.
With the persistent compile cache active (:mod:`~lightgbm_tpu.
compile_cache`) that bill is paid ONCE — by whoever compiles first.
This module makes "whoever" a deliberate deployment step instead of the
first production window:

* :func:`warmup_train` — declare (rows, features, config); it builds a
  synthetic dataset of that shape (or bins a provided sample file) and
  drives the REAL training path long enough to compile every program
  the production run dispatches: the fused ``lax.scan`` program for the
  declared ``fused_chunk``, the per-iteration grow program when the
  iteration count leaves a remainder, and all the eager glue ops
  (score scatter, bias add, ...).  Under ``train_row_bucketing`` the
  declared row count stands in for every window size in its pow2
  bucket.
* :func:`warmup_serve` — declare (num_iterations, num_leaves, features,
  row buckets); it builds synthetic :class:`~lightgbm_tpu.serve.packed.
  PackedEnsemble` shells at every pad combination the declared ensemble
  can realize (tree/node pads are functions of the declaration; the
  depth pad ladder is enumerated, since leaf-wise growth's realized
  depth is data-dependent) and compiles the packed traversal for each
  requested row bucket.

Entry points: ``lightgbm-tpu warmup`` (CLI, ``task=warmup``) and the
``LGBM_WarmupTrain`` / ``LGBM_WarmupServe`` C-ABI calls — so a
deployment can pre-fill its cache dir from a container init hook in
either language.  docs/ColdStart.md documents which parameters shape
traces (and therefore must match the declaration).

What warmup costs: one short synthetic training run per declared shape
(one fused chunk + any remainder — NOT the full iteration count; the
fused program's compile is iteration-count-independent) plus one
zero-batch predict per serving bucket.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

import numpy as np

from . import compile_cache, obs
from .config import Config
from .utils.log import LightGBMError, log_info

__all__ = ["warmup_train", "warmup_serve", "run_warmup"]


def _synth_dataset(rows: int, features: int, cfg: Config):
    """Synthetic (rows, features) BinnedDataset with objective-shaped
    labels, generated ON DEVICE (the host never holds the bulk matrix).
    Dense standard-normal features bin to the full ``max_bin`` ladder —
    the shape continuous production features realize; sparse/low-
    cardinality deployments should warm up from a ``data=`` sample file
    instead so (groups, bins) match exactly."""
    import jax
    import jax.numpy as jnp

    from .data.dataset import BinnedDataset

    key = jax.random.PRNGKey(20260803)
    kx, ky = jax.random.split(key)
    x = jax.random.normal(kx, (int(rows), int(features)), jnp.float32)
    ds = BinnedDataset.construct_from_device_matrix(x, cfg)
    obj = str(cfg.objective)
    if obj in ("binary", "cross_entropy", "cross_entropy_lambda"):
        y = (jax.random.uniform(ky, (int(rows),)) < 0.5)
        label = np.asarray(y, np.float32)
    elif obj in ("multiclass", "multiclassova"):
        label = np.asarray(
            jax.random.randint(ky, (int(rows),), 0,
                               max(int(cfg.num_class), 2)), np.float32)
    elif obj in ("poisson", "gamma", "tweedie"):
        label = np.abs(np.asarray(jax.random.normal(ky, (int(rows),)),
                                  np.float32)) + 0.1
    else:
        label = np.asarray(jax.random.normal(ky, (int(rows),)),
                           np.float32)
    ds.metadata.set_label(label)
    return ds


def _warmup_iters(num_iterations: int, chunk: int) -> int:
    """Iterations that compile the SAME program set the full run needs:
    one fused chunk (the program is iteration-count-independent) plus
    the per-iteration remainder when the count doesn't divide evenly.

    Covers drivers that chunk purely by ``fused_chunk`` (the windowed
    C-API harness's UpdateChunked, ``train_chunked`` itself).  A driver
    that ALSO caps dispatches at eval/snapshot boundaries
    (``engine.train`` with valid sets, the CLI with ``metric_freq``)
    can emit additional scan lengths (e.g. 100 iterations, chunk 20,
    eval every 25 -> lengths 20 AND 5); those compile on first use —
    declare a ``fused_chunk`` that divides the eval cadence to keep a
    fully warm start (docs/ColdStart.md)."""
    n = max(int(num_iterations), 1)
    chunk = max(int(chunk), 0)
    if chunk <= 1 or n <= chunk:
        return n
    rem = n % chunk
    return chunk + rem


def warmup_train(rows: int, features: int = 0,
                 params: Optional[dict] = None,
                 config: Optional[Config] = None,
                 dataset=None) -> dict:
    """Precompile the training program family for one declared shape.

    ``rows``/``features`` declare the training matrix; ``params`` (or a
    ready ``config``) declare everything that shapes traces —
    ``num_leaves``, ``max_bin``, ``fused_chunk``, ``num_iterations``,
    bagging/feature_fraction, ``grad_quant_bits``, ``compile_cache_dir``.
    Pass ``dataset`` (a constructed BinnedDataset, e.g. from a sample
    file) to warm the exact binned structure instead of the synthetic
    dense one.  Returns a report dict with the compile-cache counter
    delta and elapsed seconds.
    """
    from .boosting import create_boosting

    cfg = config if config is not None else Config(params or {})
    compile_cache.configure_from_config(cfg)
    before = compile_cache.counters()
    t0 = time.perf_counter()
    with obs.span("warmup.train", cat="warmup", rows=int(rows)):
        if dataset is None:
            if int(rows) <= 0 or int(features) <= 0:
                raise LightGBMError(
                    "warmup_train needs rows > 0 and features > 0 "
                    "(or an explicit dataset)")
            dataset = _synth_dataset(int(rows), int(features), cfg)
        bst = create_boosting(cfg)
        bst.init_train(dataset)
        chunk = max(int(getattr(cfg, "fused_chunk", 20)), 0)
        iters = _warmup_iters(cfg.num_iterations, chunk)
        bst.train_chunked(iters, chunk=chunk if chunk > 1 else 1)
        import jax
        jax.block_until_ready(bst.train_score)
    after = compile_cache.counters()
    report = {
        "kind": "train",
        "rows": int(dataset.num_data),
        "row_bucket": (int(bst._grower.row_bucket)
                       if bst._grower is not None else None),
        "features": int(dataset.num_features),
        "iterations_run": iters,
        "fused_chunk": chunk,
        "device_growth": bst._grower is not None,
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "cache_dir": compile_cache.cache_dir(),
        "cache_misses": after["misses"] - before["misses"],
        "cache_hits": after["hits"] - before["hits"],
    }
    log_info(f"[warmup] train shape ({report['rows']}, "
             f"{report['features']}) bucket={report['row_bucket']} "
             f"compiled in {report['elapsed_s']}s "
             f"(persistent-cache misses={report['cache_misses']}, "
             f"hits={report['cache_hits']})")
    return report


def _depth_pads(num_leaves: int) -> List[int]:
    """Every depth pad a ``num_leaves``-leaf ensemble can realize:
    leaf-wise growth's structural depth lands anywhere in
    [ceil(log2(L)), L-1], and serve/packed.py pads it to pow2 (min 8) —
    enumerate the pads so every possibility compiles."""
    from .serve.packed import _depth_pad

    lo = max(int(np.ceil(np.log2(max(num_leaves, 2)))), 1)
    hi = max(int(num_leaves) - 1, 1)
    pads = sorted({_depth_pad(d) for d in range(lo, hi + 1)})
    return pads


def _shape_family(num_leaves: int) -> List[tuple]:
    """Every (node pad, depth pad) combination a ``num_leaves``
    declaration can realize.  BOTH pads are data-dependent:
    ``pack_ensemble`` pads nodes to pow2 of the REALIZED max node count
    (easy data may top trees out well below the declared budget), and
    structural depth is bounded by the realized node count — so the
    family enumerates node pads pow2(1)..pow2(L-1) and, per node pad,
    the depth pads reachable under it."""
    from .serve.packed import _depth_pad, _pow2_at_least

    m_max = max(int(num_leaves) - 1, 1)
    out = []
    for np2 in sorted({_pow2_at_least(m) for m in range(1, m_max + 1)}):
        for dp in sorted({_depth_pad(d)
                          for d in range(1, min(np2, m_max) + 1)}):
            out.append((np2, dp))
    return out


def _synth_packed(num_iterations: int, num_leaves: int, num_features: int,
                  depth_pad: int, num_model: int = 1,
                  nodes_pad: Optional[int] = None):
    """A PackedEnsemble SHELL with the pads the declared ensemble
    realizes: every internal node routes to leaf 0, values are zero.
    Compilation only depends on shapes and the static aux, so the
    traversal program this shell compiles is byte-for-byte the one real
    models of the same declaration dispatch into."""
    import jax.numpy as jnp

    from .serve.packed import PackedEnsemble, _pow2_at_least

    k = max(int(num_model), 1)
    i_pad = _pow2_at_least(max(int(num_iterations), 1))
    t_pad = i_pad * k
    n_pad = (int(nodes_pad) if nodes_pad
             else _pow2_at_least(max(int(num_leaves) - 1, 1)))
    l_pad = n_pad + 1
    zi = jnp.zeros((t_pad, n_pad), jnp.int32)
    zf = jnp.zeros((t_pad, n_pad), jnp.float32)
    neg = jnp.full((t_pad, n_pad), -1, jnp.int32)
    return PackedEnsemble(
        split_feature=zi, threshold_hi=zf, threshold_lo=zf,
        decision_type=zi, left_child=neg, right_child=neg,
        cat_start=zi, cat_len=zi,
        cat_words=jnp.zeros((1,), jnp.uint32),
        leaf_value=jnp.zeros((t_pad, l_pad), jnp.float32),
        is_stump=jnp.zeros((t_pad,), bool),
        num_model=k, max_depth=int(depth_pad),
        # the REAL (unpadded) count, like pack_ensemble sets it:
        # num_trees rides in the treedef aux, so the in-process jit
        # cache keys on it — a t_pad value here would warm an entry no
        # real model ever dispatches into
        num_trees=max(int(num_iterations), 1) * k,
        num_features=max(int(num_features), 1))


def warmup_serve(rows: Sequence[int], features: int,
                 params: Optional[dict] = None,
                 config: Optional[Config] = None) -> dict:
    """Precompile the packed-forest traversal family for a declared
    serving deployment: every (node pad x depth pad x row bucket)
    combination the declared (num_iterations, num_leaves, features)
    ensemble can dispatch — node and depth pads are enumerated because
    both depend on the trees the data actually grows.  ``rows`` is the
    batch-row bucket list; empty falls back to the PredictionServer
    warmup defaults (128/1024/8192 plus the ``device_predict_min_rows``
    bucket).  Caveat: the tree-count pad assumes the declared
    ``num_iterations`` are all trained; a window that stops early (no
    splittable leaves) serves fewer trees and may compile fresh."""
    from .serve.engine import warmup_bucket_ladder
    from .serve.packed import predict_scores, row_bucket

    cfg = config if config is not None else Config(params or {})
    compile_cache.configure_from_config(cfg)
    before = compile_cache.counters()
    t0 = time.perf_counter()
    buckets = [int(r) for r in rows if int(r) > 0]
    if not buckets:
        buckets = warmup_bucket_ladder(
            getattr(cfg, "device_predict_min_rows", None))
    buckets = sorted({row_bucket(b) for b in buckets})
    family = _shape_family(int(cfg.num_leaves))
    compiled = []
    with obs.span("warmup.serve", cat="warmup"):
        for n_pad, d_pad in family:
            pe = _synth_packed(int(cfg.num_iterations),
                               int(cfg.num_leaves), int(features),
                               d_pad, max(int(cfg.num_class), 1),
                               nodes_pad=n_pad)
            for b in buckets:
                predict_scores(pe, np.zeros((b, int(features))),
                               min_bucket=b)
                compiled.append((n_pad, d_pad, b))
    after = compile_cache.counters()
    report = {
        "kind": "serve",
        "row_buckets": buckets,
        "node_pads": sorted({n for n, _ in family}),
        "depth_pads": sorted({d for _, d in family}),
        "programs": len(compiled),
        "features": int(features),
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "cache_dir": compile_cache.cache_dir(),
        "cache_misses": after["misses"] - before["misses"],
        "cache_hits": after["hits"] - before["hits"],
    }
    log_info(f"[warmup] serve {len(compiled)} programs "
             f"({len(family)} (node, depth) pads x row buckets "
             f"{buckets}) in {report['elapsed_s']}s "
             f"(persistent-cache misses={report['cache_misses']}, "
             f"hits={report['cache_hits']})")
    return report


def run_warmup(cfg: Config) -> List[dict]:
    """CLI driver (``lightgbm-tpu warmup`` / ``task=warmup``): warm
    every declared training row count and the declared serving buckets.

    Declaration params: ``warmup_rows`` (list of training row counts),
    ``warmup_features`` (shape's feature count), ``warmup_serve_rows``
    (serving batch buckets; empty = server defaults).  A ``data=`` file
    warms that file's exact binned structure instead of synthetic
    features.  The rest of the config IS the declaration — pass the
    same parameters the production run will use.
    """
    reports: List[dict] = []
    obs.configure_from_config(cfg)
    if compile_cache.configure_from_config(cfg) is None:
        log_info("[warmup] no compile_cache_dir/LGBM_TPU_COMPILE_CACHE "
                 "set: programs compile into this process only")
    rows_list = [int(r) for r in (cfg.warmup_rows or [])]
    features = int(getattr(cfg, "warmup_features", 0) or 0)
    if getattr(cfg, "data", ""):
        from .cli import _load_dataset
        ds = _load_dataset(cfg.data, cfg)
        reports.append(warmup_train(ds.num_data, ds.num_features,
                                    config=cfg, dataset=ds))
        features = features or int(ds.num_features)
    for rows in rows_list:
        reports.append(warmup_train(rows, features, config=cfg))
    serve_raw = list(cfg.warmup_serve_rows or [])
    if serve_raw and features > 0:
        # explicit opt-in; an entry of 0 (or all-zero) means "the
        # PredictionServer default buckets"
        serve_rows = [int(r) for r in serve_raw if int(r) > 0]
        reports.append(warmup_serve(serve_rows, features, config=cfg))
    if not reports:
        raise LightGBMError(
            "task=warmup needs a declared shape: set warmup_rows=... "
            "and warmup_features=... (or data=<sample file>)")
    return reports
