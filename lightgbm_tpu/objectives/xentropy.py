"""Cross-entropy objectives (reference ``src/objective/xentropy_objective.hpp``).

``cross_entropy``: labels are probabilities in [0, 1]; grad = sigmoid(s) - y.
``cross_entropy_lambda``: alternative parameterization with log(1+exp(s))
intensity; weighted case follows the reference's closed forms.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs as _obs
from ..utils.log import LightGBMError, log_info
from .base import ObjectiveFunction

K_EPSILON = 1e-15


def _check_labels(label):
    if (label < 0).any() or (label > 1).any():
        raise LightGBMError("[cross-entropy]: labels must be in [0, 1]")


class CrossEntropy(ObjectiveFunction):
    name = "cross_entropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        _check_labels(self.label)

    @functools.partial(jax.jit, static_argnums=0)
    def _grad(self, score, label, weights):
        z = jax.nn.sigmoid(score)
        g = z - label
        h = z * (1.0 - z)
        if weights is not None:
            g, h = g * weights, h * weights
        return g, h

    _grad = _obs.track_jit("xentropy_grad", _grad)

    def get_gradients(self, scores):
        return self._grad(scores[0].astype(jnp.float32), self.label_d,
                          self.weights_d)

    def boost_from_score(self, class_id):
        w = self.weights if self.weights is not None \
            else np.ones_like(self.label)
        pavg = float((self.label * w).sum() / max(w.sum(), K_EPSILON))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        init = math.log(pavg / (1.0 - pavg))
        log_info(f"[cross_entropy:BoostFromScore]: pavg={pavg:.6f} -> "
                 f"initscore={init:.6f}")
        return init

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-raw))


class CrossEntropyLambda(ObjectiveFunction):
    name = "cross_entropy_lambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        _check_labels(self.label)

    @functools.partial(jax.jit, static_argnums=0)
    def _grad(self, score, label, weights):
        if weights is None:
            z = jax.nn.sigmoid(score)
            return z - label, z * (1.0 - z)
        # weighted closed form (xentropy_objective.hpp:190-208)
        w, y = weights, label
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        g = (1.0 - y / jnp.maximum(z, K_EPSILON)) * w / (1.0 + enf)
        c = 1.0 / jnp.maximum(1.0 - z, K_EPSILON)
        d0 = 1.0 + epf
        a = w * epf / (d0 * d0)
        d = c - 1.0
        b = (c / jnp.maximum(d * d, K_EPSILON)) * (1.0 + w * epf - c)
        h = a * (1.0 + y * b)
        return g, h

    _grad = _obs.track_jit("xentropy_lambda_grad", _grad)

    def get_gradients(self, scores):
        return self._grad(scores[0].astype(jnp.float32), self.label_d,
                          self.weights_d)

    def boost_from_score(self, class_id):
        w = self.weights if self.weights is not None \
            else np.ones_like(self.label)
        havg = float((self.label * w).sum() / max(w.sum(), K_EPSILON))
        init = math.log(max(math.exp(havg) - 1.0, K_EPSILON))
        log_info(f"[cross_entropy_lambda:BoostFromScore]: havg={havg:.6f} -> "
                 f"initscore={init:.6f}")
        return init

    def convert_output(self, raw):
        return np.log1p(np.exp(raw))
