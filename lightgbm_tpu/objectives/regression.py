"""Regression objectives (reference ``src/objective/regression_objective.hpp``).

All gradients are elementwise jitted device ops; ``score`` arrives as a
(1, N) device array and (grad, hess) leave the same shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs as _obs
from ..utils.log import LightGBMError, log_warning
from .base import DeviceGradFn, ObjectiveFunction, percentile, weighted_percentile


def _sign(x):
    return jnp.where(x > 0, 1.0, jnp.where(x < 0, -1.0, 0.0))


@jax.jit
def _l2_grad(score, label, weights):
    """One formula for the per-iteration and fused paths; module-level
    so the jit cache survives across retrain windows and the fused-path
    wrapper retains no objective instance (which would pin its per-row
    device arrays in jit's static-arg cache for the process lifetime)."""
    diff = score - label
    w = jnp.ones_like(score) if weights is None else weights
    return diff * w, w


_l2_grad = _obs.track_jit("l2_grad", _l2_grad)


def _l2_device_fn(score, args):
    # _l2_grad inlines when traced inside the fused scan
    return _l2_grad(score, *args)


# The sibling objectives' formulas live at module level for the same
# reason as _l2_grad: a jitted instance method makes the instance a
# static arg, pinning its per-row label/weight device arrays (and one
# trace per retrain window's fresh objective) in jit's cache for the
# process lifetime.  Scalar hyper-params are static argnums — one trace
# per distinct value, exactly the per-instance behavior, minus the leak.

@jax.jit
def _l1_grad(score, label, weights):
    diff = score - label
    w = jnp.ones_like(score) if weights is None else weights
    return _sign(diff) * w, w


@functools.partial(jax.jit, static_argnums=0)
def _huber_grad(alpha, score, label, weights):
    diff = score - label
    g = jnp.where(jnp.abs(diff) <= alpha, diff, _sign(diff) * alpha)
    w = jnp.ones_like(score) if weights is None else weights
    return g * w, w


@functools.partial(jax.jit, static_argnums=0)
def _fair_grad(c, score, label, weights):
    x = score - label
    ax = jnp.abs(x)
    g = c * x / (ax + c)
    h = c * c / ((ax + c) ** 2)
    if weights is not None:
        g, h = g * weights, h * weights
    return g, h


@functools.partial(jax.jit, static_argnums=0)
def _poisson_grad(max_delta_step, score, label, weights):
    g = jnp.exp(score) - label
    h = jnp.exp(score + max_delta_step)
    if weights is not None:
        g, h = g * weights, h * weights
    return g, h


@functools.partial(jax.jit, static_argnums=0)
def _quantile_grad(alpha, score, label, weights):
    delta = score - label
    g = jnp.where(delta >= 0, 1.0 - alpha, -alpha)
    w = jnp.ones_like(score) if weights is None else weights
    return g * w, w


@jax.jit
def _mape_grad(score, label, label_weight, weights):
    diff = score - label
    g = _sign(diff) * label_weight
    h = jnp.ones_like(score) if weights is None else weights
    return g, h


@jax.jit
def _gamma_grad(score, label, weights):
    g = 1.0 - label * jnp.exp(-score)
    h = label * jnp.exp(-score)
    if weights is not None:
        g, h = g * weights, h * weights
    return g, h


@functools.partial(jax.jit, static_argnums=0)
def _tweedie_grad(rho, score, label, weights):
    e1 = jnp.exp((1.0 - rho) * score)
    e2 = jnp.exp((2.0 - rho) * score)
    g = -label * e1 + e2
    h = -label * (1.0 - rho) * e1 + (2.0 - rho) * e2
    if weights is not None:
        g, h = g * weights, h * weights
    return g, h


_l1_grad = _obs.track_jit("l1_grad", _l1_grad)
_huber_grad = _obs.track_jit("huber_grad", _huber_grad)
_fair_grad = _obs.track_jit("fair_grad", _fair_grad)
_poisson_grad = _obs.track_jit("poisson_grad", _poisson_grad)
_quantile_grad = _obs.track_jit("quantile_grad", _quantile_grad)
_mape_grad = _obs.track_jit("mape_grad", _mape_grad)
_gamma_grad = _obs.track_jit("gamma_grad", _gamma_grad)
_tweedie_grad = _obs.track_jit("tweedie_grad", _tweedie_grad)


class RegressionL2(ObjectiveFunction):
    """L2 loss; grad = (score - label) [* w], hess = 1 [* w]
    (regression_objective.hpp:64-140)."""

    name = "regression"
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = bool(getattr(config, "reg_sqrt", False))

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            self.label = (np.sign(self.label)
                          * np.sqrt(np.abs(self.label))).astype(np.float32)
            self.label_d = jnp.asarray(self.label)
        self.is_constant_hessian = self.weights is None and \
            type(self) is RegressionL2

    def _grad(self, score, label, weights):
        return _l2_grad(score, label, weights)

    def get_gradients(self, scores):
        return self._grad(scores[0].astype(jnp.float32), self.label_d,
                          self.weights_d)

    def device_grad(self):
        # subclasses (L1/Huber/...) override gradients; only plain L2 is
        # known to be this formula
        if type(self) is not RegressionL2:
            return None
        # module-level fn: shares _l2_grad with the per-iteration path
        # and closes over nothing
        return (DeviceGradFn(_l2_device_fn, ("regression_l2",)),
                (self.label_d, self.weights_d))

    def boost_from_score(self, class_id):
        if self.weights is None:
            return float(np.mean(self.label))
        return float(np.sum(self.label * self.weights)
                     / max(np.sum(self.weights), 1e-35))

    def convert_output(self, raw):
        if self.sqrt:
            return np.sign(raw) * raw * raw
        return raw

    def to_string(self):
        return self.name + (" sqrt" if self.sqrt else "")


class RegressionL1(RegressionL2):
    """L1: grad = sign(diff) [* w]; leaf outputs renewed to the weighted
    median of residuals (regression_objective.hpp:175-258)."""

    name = "regression_l1"
    is_renew_tree_output = True
    alpha = 0.5

    def _grad(self, score, label, weights):
        return _l1_grad(score, label, weights)

    def boost_from_score(self, class_id):
        if self.weights is None:
            return percentile(self.label, self.alpha)
        return weighted_percentile(self.label, self.weights, self.alpha)

    def renew_tree_output(self, leaf_pred, residuals, weights):
        if weights is None:
            return percentile(residuals, self.alpha)
        return weighted_percentile(residuals, weights, self.alpha)


class Huber(RegressionL2):
    """Huber loss with transition alpha (regression_objective.hpp:261-320)."""

    name = "huber"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.alpha)
        self.sqrt = False

    def _grad(self, score, label, weights):
        return _huber_grad(self.alpha, score, label, weights)


class Fair(RegressionL2):
    """Fair loss (regression_objective.hpp:323-369)."""

    name = "fair"
    is_constant_hessian = False

    def __init__(self, config):
        super().__init__(config)
        self.c = float(config.fair_c)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.is_constant_hessian = False

    def _grad(self, score, label, weights):
        return _fair_grad(self.c, score, label, weights)


class Poisson(RegressionL2):
    """Poisson with log link: grad = exp(s) - y, hess = exp(s + mds)
    (regression_objective.hpp:371-450)."""

    name = "poisson"
    is_constant_hessian = False

    def __init__(self, config):
        super().__init__(config)
        self.max_delta_step = float(config.poisson_max_delta_step)
        self.sqrt = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.is_constant_hessian = False
        if (self.label < 0).any():
            raise LightGBMError(
                f"[{self.name}]: at least one target label is negative")
        if self.label.sum() == 0:
            raise LightGBMError(f"[{self.name}]: sum of labels is zero")

    def _grad(self, score, label, weights):
        return _poisson_grad(self.max_delta_step, score, label, weights)

    def boost_from_score(self, class_id):
        return float(np.log(max(RegressionL2.boost_from_score(self, 0),
                                1e-35)))

    def convert_output(self, raw):
        return np.exp(raw)


class Quantile(RegressionL2):
    """Pinball loss at quantile alpha (regression_objective.hpp:452-549)."""

    name = "quantile"
    is_renew_tree_output = True

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.alpha)
        if not (0.0 < self.alpha < 1.0):
            raise LightGBMError("alpha should be in (0, 1) for quantile")

    def _grad(self, score, label, weights):
        return _quantile_grad(self.alpha, score, label, weights)

    def boost_from_score(self, class_id):
        if self.weights is None:
            return percentile(self.label, self.alpha)
        return weighted_percentile(self.label, self.weights, self.alpha)

    def renew_tree_output(self, leaf_pred, residuals, weights):
        if weights is None:
            return percentile(residuals, self.alpha)
        return weighted_percentile(residuals, weights, self.alpha)


class Mape(RegressionL1):
    """MAPE: sign(diff) / max(1, |y|) with median renewal weighted by the
    label weight (regression_objective.hpp:551-650)."""

    name = "mape"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if (np.abs(self.label) < 1).any():
            log_warning("Met 'abs(label) < 1', will convert them to '1' in "
                        "MAPE objective and metric")
        lw = 1.0 / np.maximum(1.0, np.abs(self.label))
        if self.weights is not None:
            lw = lw * self.weights
        self.label_weight = lw.astype(np.float32)
        self.label_weight_d = jnp.asarray(self.label_weight)

    def get_gradients(self, scores):
        return _mape_grad(scores[0].astype(jnp.float32), self.label_d,
                          self.label_weight_d, self.weights_d)

    def boost_from_score(self, class_id):
        return weighted_percentile(self.label, self.label_weight, 0.5)

    def renew_tree_output(self, leaf_pred, residuals, weights):
        # weights passed here are the label weights of the leaf rows
        return weighted_percentile(residuals, weights, 0.5)


class Gamma(Poisson):
    """Gamma deviance with log link (regression_objective.hpp:652-687)."""

    name = "gamma"

    def init(self, metadata, num_data):
        RegressionL2.init(self, metadata, num_data)
        self.is_constant_hessian = False
        if (self.label <= 0).any():
            raise LightGBMError(
                f"[{self.name}]: labels must be positive")

    def _grad(self, score, label, weights):
        return _gamma_grad(score, label, weights)


class Tweedie(Poisson):
    """Tweedie with variance power rho (regression_objective.hpp:689-740)."""

    name = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def _grad(self, score, label, weights):
        return _tweedie_grad(self.rho, score, label, weights)
