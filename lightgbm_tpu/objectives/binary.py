"""Binary logloss objective (reference ``src/objective/binary_objective.hpp``)."""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs as _obs
from ..utils.log import LightGBMError, log_info, log_warning
from .base import DeviceGradFn, ObjectiveFunction

K_EPSILON = 1e-15


@functools.partial(jax.jit, static_argnums=0)
def _logloss_grad(sigmoid, score, sign_label, label_weight, weights):
    """One formula for the per-iteration and fused paths.  Module-level
    (keyed on the sigmoid value, not an objective instance) so the jit
    cache survives across retrain windows and the fused-path wrapper
    does not have to close over the objective — a closed-over instance
    would pin its per-row device arrays in jit's static-arg cache for
    the process lifetime (retrain-every-window harness)."""
    response = (-sign_label * sigmoid
                / (1.0 + jnp.exp(sign_label * sigmoid * score)))
    abs_r = jnp.abs(response)
    g = response * label_weight
    h = abs_r * (sigmoid - abs_r) * label_weight
    if weights is not None:
        g, h = g * weights, h * weights
    return g, h


_logloss_grad = _obs.track_jit("binary_grad", _logloss_grad)


class BinaryLogloss(ObjectiveFunction):
    """Labels {0,1} mapped to {-1,+1}; sigmoid-scaled logistic gradients with
    is_unbalance / scale_pos_weight label weighting
    (binary_objective.hpp:13-165)."""

    name = "binary"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)
        if self.sigmoid <= 0.0:
            raise LightGBMError("sigmoid param must be greater than zero")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        is_pos = self.label > 0
        cnt_pos = int(is_pos.sum())
        cnt_neg = num_data - cnt_pos
        self.need_train = True
        if cnt_pos == 0 or cnt_neg == 0:
            log_warning("Contains only one class")
            self.need_train = False
        log_info(f"Number of positive: {cnt_pos}, number of negative: {cnt_neg}")
        w_pos, w_neg = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self.cnt_pos, self.cnt_neg = cnt_pos, cnt_neg
        self.sign_label_d = jnp.asarray(np.where(is_pos, 1.0, -1.0), jnp.float32)
        self.label_weight_d = jnp.asarray(np.where(is_pos, w_pos, w_neg),
                                          jnp.float32)

    def _grad(self, score, sign_label, label_weight, weights):
        return _logloss_grad(self.sigmoid, score, sign_label,
                             label_weight, weights)

    def get_gradients(self, scores):
        return self._grad(scores[0].astype(jnp.float32), self.sign_label_d,
                          self.label_weight_d, self.weights_d)

    def device_grad(self):
        if not self.need_train:
            return None
        sigmoid = self.sigmoid   # close over the scalar, NOT self

        def fn(score, args):
            # _logloss_grad inlines when traced inside the fused scan,
            # so the fused and per-iteration paths share one formula
            return _logloss_grad(sigmoid, score, *args)

        # sigmoid is the only static fact of the trace beyond the args
        # pytree (weights None-ness lives in the pytree structure)
        return (DeviceGradFn(fn, ("binary", sigmoid)),
                (self.sign_label_d, self.label_weight_d, self.weights_d))

    def boost_from_score(self, class_id):
        is_pos = (self.label > 0).astype(np.float64)
        if self.weights is not None:
            suml = float((is_pos * self.weights).sum())
            sumw = float(self.weights.sum())
        else:
            suml = float(is_pos.sum())
            sumw = float(self.num_data)
        pavg = min(max(suml / max(sumw, K_EPSILON), K_EPSILON), 1.0 - K_EPSILON)
        init_score = math.log(pavg / (1.0 - pavg)) / self.sigmoid
        log_info(f"[binary:BoostFromScore]: pavg={pavg:.6f} -> "
                 f"initscore={init_score:.6f}")
        return init_score

    def class_need_train(self, class_id):
        return self.need_train

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"binary sigmoid:{self.sigmoid}"
