"""Objective base class (reference ``include/LightGBM/objective_function.h``).

Scores are device arrays of shape (num_model, N) — the analog of the
reference's class-major flat layout.  ``get_gradients`` returns device
(num_model, N) float32 (grad, hess); everything elementwise runs jitted.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np


class DeviceGradFn:
    """Hashable wrapper for a fused-path gradient function.

    ``DeviceGrower.fused_train`` passes ``grad_fn`` as a jax.jit STATIC
    argument, and jit compares static args by ``__eq__``/``__hash__`` —
    for a plain closure that is identity, so the fresh closure each new
    GBDT window builds would re-trace (and re-compile) the whole fused
    scan despite the process-level program cache hitting.  ``key`` must
    capture EVERY static fact the gradient trace depends on beyond the
    ``args`` pytree (scalar hyper-params, closed-over tables): equal
    keys reuse the first wrapper's compiled trace verbatim.
    """

    __slots__ = ("fn", "key")

    def __init__(self, fn, key: tuple):
        self.fn = fn
        self.key = key

    def __call__(self, score, args):
        return self.fn(score, args)

    def __eq__(self, other):
        return isinstance(other, DeviceGradFn) and other.key == self.key

    def __hash__(self):
        return hash(self.key)

    def __repr__(self):
        return f"DeviceGradFn{self.key!r}"

    @property
    def obs_signature(self) -> str:
        # obs jit tracking represents callables by __qualname__, which
        # cannot distinguish wrapper instances; the key can
        return repr(self)


class ObjectiveFunction:
    name = "none"
    is_constant_hessian = False
    is_renew_tree_output = False
    # whether device_grad's formula is row-local: row i's (grad, hess)
    # depend only on row i's (score, label, weight), and the output
    # shape follows the input score shape.  Gates train_row_bucketing's
    # fused path: bucket-padded rows then produce garbage gradients the
    # grower's valid mask can safely zero.  Objectives with cross-row
    # structure (lambdarank's query segments) must set this False — a
    # padded row could change REAL rows' gradients there.
    device_grad_rowwise = True

    def __init__(self, config):
        self.config = config

    @property
    def num_model_per_iteration(self) -> int:
        return 1

    def init(self, metadata, num_data: int):
        self.num_data = num_data
        self.label = np.asarray(metadata.label, np.float32) \
            if metadata.label is not None else np.zeros(num_data, np.float32)
        self.weights = (np.asarray(metadata.weights, np.float32)
                        if metadata.weights is not None else None)
        self.label_d = jnp.asarray(self.label)
        self.weights_d = (jnp.asarray(self.weights)
                          if self.weights is not None else None)

    def get_gradients(self, scores) -> Tuple[jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError

    def device_grad(self):
        """Pure-jnp gradient for fusing into a device-resident training
        loop (``DeviceGrower.fused_train``): returns ``(fn, args)`` where
        ``fn(score_1d, args) -> (grad, hess)`` is safe to trace inside
        jit/scan — no host work, and every array it reads arrives through
        ``args`` (a pytree passed as a jit argument; a closed-over device
        array would be baked into the compile request as a constant).
        Returns None when the objective has no fusable single-model
        formulation (multi-model, renewal, host-side state).
        """
        return None

    def boost_from_score(self, class_id: int) -> float:
        """Initial score (BoostFromScore)."""
        return 0.0

    def class_need_train(self, class_id: int) -> bool:
        return True

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        """Raw score -> user-facing prediction (ConvertOutput)."""
        return raw

    def renew_tree_output(self, leaf_pred: float, residual_fn) -> float:
        """Per-leaf output renewal for percentile-style objectives."""
        raise NotImplementedError

    def to_string(self) -> str:
        return self.name

    def _w(self, x):
        return x if self.weights_d is None else x * self.weights_d


def percentile(data: np.ndarray, alpha: float) -> float:
    """Reference PercentileFun (regression_objective.hpp:11-36): descending
    order, float position (1-alpha)*cnt, linear interpolation."""
    data = np.asarray(data, np.float64)
    cnt = len(data)
    if cnt == 0:
        return 0.0
    d = np.sort(data)[::-1]
    float_pos = (1.0 - alpha) * cnt
    pos = int(float_pos)
    if pos < 1:
        return float(d[0])
    if pos >= cnt:
        return float(d[-1])
    bias = float_pos - pos
    return float(d[pos - 1] - (d[pos - 1] - d[pos]) * bias)


def weighted_percentile(data: np.ndarray, weights: np.ndarray,
                        alpha: float) -> float:
    """Reference WeightedPercentileFun (regression_objective.hpp:39-59) with
    a bounds-safe interpolation (the reference indexes one past the cdf when
    the threshold lands in the final interval)."""
    data = np.asarray(data, np.float64)
    cnt = len(data)
    if cnt == 0:
        return 0.0
    order = np.argsort(data, kind="stable")
    cdf = np.cumsum(np.asarray(weights, np.float64)[order])
    thr = cdf[-1] * alpha
    pos = int(np.searchsorted(cdf, thr, side="right"))
    if pos == 0:
        return float(data[order[0]])
    if pos >= cnt:
        return float(data[order[-1]])
    v1, v2 = data[order[pos - 1]], data[order[pos]]
    denom = cdf[pos] - cdf[pos - 1]
    frac = (thr - cdf[pos - 1]) / denom if denom > 0 else 0.0
    return float(v1 + frac * (v2 - v1))
