"""LambdarankNDCG objective (reference ``src/objective/rank_objective.hpp``).

TPU-native formulation: instead of the reference's per-query scalar pair
loops, queries are padded into power-of-two length buckets and every
(doc_i, doc_j) pair of a query is evaluated as a (P, P) matrix — sort by
score, broadcast deltas, mask invalid/equal-label pairs, and row/column-sum
the pairwise lambdas.  Queries are processed in fixed-size batches via
``lax.map`` to bound the P^2 working set.

Differences from the reference kept deliberately: the sigmoid is computed
exactly instead of via the 1024-entry lookup table
(``ConstructSigmoidTable``, rank_objective.hpp:183-200) — same function,
no quantization error.
"""

from __future__ import annotations

import functools
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs as _obs
from ..utils.log import LightGBMError
from .base import DeviceGradFn, ObjectiveFunction

_PAIR_BUDGET = 1 << 24   # floats in flight per batch (P*P*B)


def default_label_gain(n=31) -> List[float]:
    return [float((1 << i) - 1) for i in range(n)]


class LambdarankNDCG(ObjectiveFunction):
    name = "lambdarank"
    # gradients are query-segment reductions gathered through the
    # per-row bucket permutation (inv_perm is sized to the REAL row
    # count): bucket-padding the score would both break the output
    # shape and let padding perturb real rows — train_row_bucketing's
    # fused path must stay off here (ops/grow.py, docs/ColdStart.md)
    device_grad_rowwise = False

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        gains = list(config.label_gain or [])
        self.label_gain = [float(g) for g in gains] or default_label_gain()
        self.max_position = int(getattr(config, "max_position", 20) or 20)
        if self.sigmoid <= 0:
            raise LightGBMError("sigmoid param must be greater than zero")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        qb = metadata.query_boundaries
        if qb is None:
            raise LightGBMError(
                "Lambdarank tasks require query information")
        self.query_boundaries = np.asarray(qb, np.int64)
        num_queries = len(qb) - 1
        labels = self.label.astype(np.int32)
        if labels.max(initial=0) >= len(self.label_gain):
            raise LightGBMError(
                f"label_gain has {len(self.label_gain)} entries but labels "
                f"reach {labels.max()}; set label_gain explicitly")

        # inverse max DCG per query at truncation max_position
        # (rank_objective.hpp:56-67)
        disc = 1.0 / np.log2(np.arange(2, 2 + max(self.max_position, 1)))
        gains = np.asarray(self.label_gain, np.float64)
        inv_mdcg = np.zeros(num_queries)
        for q in range(num_queries):
            ls = np.sort(labels[qb[q]:qb[q + 1]])[::-1][:self.max_position]
            mdcg = (gains[ls] * disc[:len(ls)]).sum()
            inv_mdcg[q] = 1.0 / mdcg if mdcg > 0 else 0.0

        # bucket queries by padded length
        self._buckets: Dict[int, dict] = {}
        lengths = np.diff(qb)
        for q in range(num_queries):
            p = 8
            while p < lengths[q]:
                p <<= 1
            self._buckets.setdefault(p, {"q": []})["q"].append(q)
        flat_rows = []
        for p in sorted(self._buckets):
            b = self._buckets[p]
            qs = b["q"]
            rows = np.full((len(qs), p), num_data, np.int32)   # pad -> dummy
            labs = np.zeros((len(qs), p), np.int32)
            for i, q in enumerate(qs):
                lo, hi = qb[q], qb[q + 1]
                rows[i, :hi - lo] = np.arange(lo, hi)
                labs[i, :hi - lo] = labels[lo:hi]
            b["rows"] = jnp.asarray(rows)
            b["labels"] = jnp.asarray(labs)
            b["valid"] = jnp.asarray(rows != num_data)
            b["inv_mdcg"] = jnp.asarray(inv_mdcg[qs], jnp.float32)
            # clamp to the bucket's own query count: padding to a FULL
            # batch (the old `(-q) % batch`) made a 5-query bucket
            # compute 262144 padded queries of garbage — measured 260 ms
            # for 5 real queries
            b["batch"] = max(1, min(_PAIR_BUDGET // (p * p), len(qs)))
            flat_rows.append(rows.reshape(-1))
        self._gain_table = jnp.asarray(self.label_gain, jnp.float32)
        # inverse permutation: position of each data row in the
        # concatenated bucket layout, so gradients assemble with ONE
        # gather instead of per-bucket scatter-adds (measured ~200 ms
        # per scatter pass at 723k rows)
        concat = np.concatenate(flat_rows)
        pos = np.zeros(num_data + 1, np.int64)
        pos[concat] = np.arange(len(concat))
        self._inv_perm = jnp.asarray(pos[:num_data], jnp.int32)
        # static jit arguments, fixed at init (rebuilt tuples would still
        # hit the jit cache, but there is no reason to re-sort per call)
        order = sorted(self._buckets)
        self._grad_arrays = tuple(
            (self._buckets[p]["rows"], self._buckets[p]["labels"],
             self._buckets[p]["valid"], self._buckets[p]["inv_mdcg"])
            for p in order)
        self._grad_batches = tuple(self._buckets[p]["batch"]
                                   for p in order)

    def get_gradients(self, scores):
        score_ext = jnp.concatenate(
            [scores[0].astype(jnp.float32), jnp.zeros(1, jnp.float32)])
        gh = _all_grads(self._gain_table, score_ext, self._grad_arrays,
                        self._grad_batches, self.sigmoid, self._inv_perm)
        grad, hess = gh[:, 0], gh[:, 1]
        if self.weights_d is not None:
            grad = grad * self.weights_d
            hess = hess * self.weights_d
        return grad, hess

    def device_grad(self):
        # close over the small static facts only (gain table: ~31
        # floats; batches/sigmoid: scalars), NOT self — a closed-over
        # objective would pin its per-row bucket/permutation device
        # arrays in jit's static-arg cache for the process lifetime
        gain_table = self._gain_table
        sigmoid = self.sigmoid
        batches = self._grad_batches   # static ints, safe to close over

        def fn(score, args):
            # shares _all_grads with the per-iteration path (inlines
            # when traced inside the fused scan)
            bucket_arrays, inv_perm, weights = args
            score_ext = jnp.concatenate(
                [score, jnp.zeros(1, jnp.float32)])
            gh = _all_grads(gain_table, score_ext, bucket_arrays,
                            batches, sigmoid, inv_perm)
            g, h = gh[:, 0], gh[:, 1]
            if weights is not None:
                g, h = g * weights, h * weights
            return g, h

        # static facts of the trace: sigmoid + label_gain feed the
        # closed-over gain table constant, batches shape the unrolled
        # bucket loop
        return (DeviceGradFn(
            fn, ("lambdarank", sigmoid, tuple(self.label_gain),
                 batches)),
            (self._grad_arrays, self._inv_perm, self.weights_d))

    def to_string(self):
        return self.name


def _bucket_grads(gain_table, sigmoid, score_ext, rows, labels, valid,
                  inv_mdcg, batch):
    """score_ext: (N+1,) scores with trailing dummy 0."""
    p = rows.shape[1]
    disc_all = 1.0 / jnp.log2(jnp.arange(2, 2 + p, dtype=jnp.float32))

    def one_batch(args):
        r, l, v, inv = args                      # (B,P) ... (B,)
        s = score_ext[r]

        def one_query(s_q, l_q, v_q, inv_q):
            neg = jnp.where(v_q, s_q, -jnp.inf)
            order = jnp.argsort(-neg, stable=True)
            ss = s_q[order]
            ls = l_q[order]
            vs = v_q[order]
            g = gain_table[jnp.clip(ls, 0, None)]
            cnt = vs.sum()
            best = ss[0]
            worst = ss[jnp.maximum(cnt - 1, 0)]
            delta = ss[:, None] - ss[None, :]
            dgap = g[:, None] - g[None, :]
            pdisc = jnp.abs(disc_all[:, None] - disc_all[None, :])
            dndcg = dgap * pdisc * inv_q
            norm = (best != worst)
            dndcg = jnp.where(norm, dndcg / (0.01 + jnp.abs(delta)),
                              dndcg)
            mask = (vs[:, None] & vs[None, :]
                    & (ls[:, None] > ls[None, :]))
            sig = 2.0 / (1.0 + jnp.exp(2.0 * sigmoid * delta))
            lam = jnp.where(mask, -dndcg * sig, 0.0)
            hes = jnp.where(mask, 2.0 * dndcg * sig * (2.0 - sig), 0.0)
            lam_s = lam.sum(axis=1) - lam.sum(axis=0)
            hes_s = hes.sum(axis=1) + hes.sum(axis=0)
            inv_order = jnp.argsort(order, stable=True)
            return lam_s[inv_order], hes_s[inv_order]

        return jax.vmap(one_query)(s, l, v, inv)

    q = rows.shape[0]
    pad_q = (-q) % batch
    if pad_q:
        zpad = lambda a, fill: jnp.concatenate(
            [a, jnp.full((pad_q,) + a.shape[1:], fill, a.dtype)])
        rows = zpad(rows, score_ext.shape[0] - 1)
        labels = zpad(labels, 0)
        valid = zpad(valid, False)
        inv_mdcg = zpad(inv_mdcg, 0.0)
    nb = rows.shape[0] // batch
    shp = lambda a: a.reshape((nb, batch) + a.shape[1:])
    lam, hes = jax.lax.map(
        one_batch, (shp(rows), shp(labels), shp(valid), shp(inv_mdcg)))
    return lam.reshape(-1, p)[:q], hes.reshape(-1, p)[:q]


@functools.partial(jax.jit, static_argnums=(3, 4))
def _all_grads(gain_table, score_ext, bucket_arrays, batches, sigmoid,
               inv_perm):
    """All buckets in ONE compiled program: ~11 small dispatches (a
    ~6 ms tunnel floor each) collapse into one.  Module-level (keyed on
    the batches/sigmoid values, not an objective instance) so the jit
    cache survives across retrain windows and the fused-path wrapper
    does not retain the objective's per-row device arrays."""
    flats = []
    for (rows, labels, valid, inv_mdcg), batch in zip(bucket_arrays,
                                                      batches):
        lam, hes = _bucket_grads(gain_table, sigmoid, score_ext, rows,
                                 labels, valid, inv_mdcg, batch)
        flats.append(jnp.stack([lam.reshape(-1), hes.reshape(-1)], 1))
    # every data row occurs exactly once across buckets: assemble by
    # gathering the concatenated flat results at the precomputed
    # positions (one gather vs 2x buckets scatter-adds)
    return jnp.concatenate(flats)[inv_perm]


_all_grads = _obs.track_jit("rank_all_grads", _all_grads)
