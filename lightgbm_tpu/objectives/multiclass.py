"""Multiclass objectives (reference ``src/objective/multiclass_objective.hpp``).

Softmax: one tree per class per iteration, grad = p - onehot,
hess = 2 p (1 - p).  OVA wraps one BinaryLogloss per class.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs as _obs
from ..utils.log import LightGBMError
from .base import ObjectiveFunction
from .binary import BinaryLogloss


class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)

    @property
    def num_model_per_iteration(self):
        return self.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        li = self.label.astype(np.int32)
        if (li < 0).any() or (li >= self.num_class).any():
            raise LightGBMError(
                "Label must be in [0, num_class) for multiclass objective")
        self.label_int_d = jnp.asarray(li)
        # per-class init probabilities (weighted); classes with degenerate
        # probability are skipped entirely (SkipEmptyClass behaviour)
        w = self.weights if self.weights is not None else np.ones(num_data)
        self.class_init_probs = [
            float((w * (li == k)).sum() / max(w.sum(), 1e-35))
            for k in range(self.num_class)]

    @functools.partial(jax.jit, static_argnums=0)
    def _grad(self, scores, label_int, weights):
        # scores (K, N): softmax across classes
        p = jax.nn.softmax(scores, axis=0)
        onehot = (jnp.arange(self.num_class)[:, None] == label_int[None, :])
        g = p - onehot.astype(p.dtype)
        h = 2.0 * p * (1.0 - p)
        if weights is not None:
            g, h = g * weights[None, :], h * weights[None, :]
        return g, h

    _grad = _obs.track_jit("multiclass_grad", _grad)

    def get_gradients(self, scores):
        return self._grad(scores.astype(jnp.float32), self.label_int_d,
                          self.weights_d)

    def boost_from_score(self, class_id):
        # log of the class prior (multiclass_objective.hpp:137-139)
        return float(np.log(max(1e-15, self.class_init_probs[class_id])))

    def class_need_train(self, class_id):
        p = self.class_init_probs[class_id]
        return not (abs(p) <= 1e-15 or abs(p) >= 1.0 - 1e-15)

    def convert_output(self, raw):
        """raw (K, N) -> softmax probabilities."""
        e = np.exp(raw - raw.max(axis=0, keepdims=True))
        return e / e.sum(axis=0, keepdims=True)

    def to_string(self):
        return f"multiclass num_class:{self.num_class}"


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.sigmoid = float(config.sigmoid)
        self._binaries = [BinaryLogloss(config) for _ in range(self.num_class)]

    @property
    def num_model_per_iteration(self):
        return self.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        for k, b in enumerate(self._binaries):

            class _View:
                pass

            view = _View()
            view.label = (self.label.astype(np.int32) == k).astype(np.float32)
            view.weights = self.weights
            b.init(view, num_data)

    def get_gradients(self, scores):
        gs, hs = [], []
        for k, b in enumerate(self._binaries):
            g, h = b.get_gradients(scores[k:k + 1])
            gs.append(g)
            hs.append(h)
        return jnp.stack(gs), jnp.stack(hs)

    def boost_from_score(self, class_id):
        return self._binaries[class_id].boost_from_score(0)

    def class_need_train(self, class_id):
        return self._binaries[class_id].class_need_train(0)

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))

    def to_string(self):
        return (f"multiclassova num_class:{self.num_class} "
                f"sigmoid:{self.sigmoid}")
