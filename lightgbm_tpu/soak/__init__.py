"""Composed N-node CDN-fleet chaos soak, gated on the SLO engine.

The subsystem that runs the fork's pieces *together* at production
shape (ROADMAP item 4): an M-tenant ``FleetServer`` retrained per
tenant through ``RetrainPipeline`` under a deterministic seed-keyed
fault timeline, with the verdict gated on ``obs/slo.py`` plus
harness-level invariants (resume byte-identity, zero-retrace swaps,
throughput vs the committed reference).  See docs/Soak.md.
"""

from .scenario import (FaultEvent, SoakScenario, compile_timeline,
                       fault_spec, timeline_digest)
from .driver import SoakDriver, run_scenario
from .report import (build_verdict, run_and_report, strip_volatile,
                     write_verdict)

__all__ = [
    "FaultEvent", "SoakScenario", "SoakDriver",
    "build_verdict", "compile_timeline", "fault_spec",
    "run_and_report", "run_scenario", "strip_volatile",
    "timeline_digest", "write_verdict",
]
