"""Declarative chaos-soak scenarios (docs/Soak.md).

A :class:`SoakScenario` describes an M-node learned-CDN fleet — one
``FleetServer`` tenant per cache node, each retrained on its own
cadence through ``RetrainPipeline(server=fleet, tenant_id=m)`` — plus
the chaos to inject while it runs.  The scenario compiles to a
**deterministic seed-keyed fault timeline**: every kill / device-death
burst / poisoned micro-batch / dead ingest peer / clock skew is placed
by a sha256 hash of ``(seed, kind, ...)`` (the same derivation idiom
as ``robust/faults._hash_uniform``), so the same seed replays the same
chaos byte-for-byte.  The timeline lowers to one combined
``LGBM_TPU_FAULTS`` spec string (armed ONCE, up front — arming resets
invocation counters) plus process-level event records the driver
executes at their scheduled points.

Workload: each tenant's windows replay the paper's cache-admission
shape — a Zipf/lognormal request trace per (seed, tenant, window),
relaxed-Belady (OPT) admission labels, gap-feature CSR rows — reusing
``examples/cache_admission.py``'s derivation verbatim.  Rows per
window are trimmed to exactly ``sample_rows`` so every retrain window
is shape-stable (the zero-retrace swap gate depends on it).
"""

from __future__ import annotations

import dataclasses
import hashlib
import importlib.util
import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..basic import LightGBMError

ENV_SCENARIO = "LGBM_TPU_SOAK"

# examples/cache_admission.py feature layout: 50 gap features +
# size/cacheAvail/cost
NUM_FEATURES = 53

# the fork's committed cache-admission reference: 125.4 s for 20M
# sampled rows on the 8-chip config (ROADMAP.md) -> 6.27 s / 1M rows
REFERENCE_S_PER_1M_ROWS = 125.4 / 20.0

DEFAULT_SLO = ("availability>=0.999,p95_ms<=250,burn<=14;"
               "source=serve.fleet;window_s=600")

_CA_LOCK = threading.Lock()
_CA_MODULE = None


def _cache_admission():
    """The examples/cache_admission.py module (not a package; loaded by
    path the way bench.py does)."""
    global _CA_MODULE
    with _CA_LOCK:
        if _CA_MODULE is None:
            root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            path = os.path.join(root, "examples", "cache_admission.py")
            spec = importlib.util.spec_from_file_location(
                "lgbm_tpu_soak_cache_admission", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _CA_MODULE = mod
        return _CA_MODULE


def _hu(*parts) -> float:
    """Deterministic uniform in [0, 1) keyed on ``parts`` (the
    ``robust/faults._hash_uniform`` sha256 idiom)."""
    h = hashlib.sha256(
        "\x1f".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


def _hseed(*parts) -> int:
    """Deterministic 31-bit RNG seed keyed on ``parts``."""
    h = hashlib.sha256(
        "\x1f".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:4], "big") & 0x7FFFFFFF


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled chaos event.

    ``kind`` ∈ {kill, device_death, poison, dead_peer, clock_skew}.
    ``tenant``/``window`` locate pipeline-side events (kill,
    dead_peer); ``tick`` locates load-thread events (poison,
    dead_peer's armed budget index); ``at`` is the armed rule's
    invocation index where one applies.
    """

    kind: str
    tenant: int = -1
    window: int = -1
    tick: int = -1
    at: int = -1
    site: str = ""

    def to_json(self) -> dict:
        out = {"kind": self.kind}
        for k in ("tenant", "window", "tick", "at"):
            v = getattr(self, k)
            if v >= 0:
                out[k] = v
        if self.site:
            out["site"] = self.site
        return out


@dataclass
class SoakScenario:
    """Everything one soak run needs, JSON-serializable.

    Chaos knobs count EVENTS, not probabilities: ``kills`` schedules
    that many kill-and-resume points across tenants' retrain windows
    (window >= 1, so there is always a checkpoint to resume from);
    ``device_deaths`` schedules transient dispatch-fault bursts on the
    serving path (``device_death_persist`` makes the device stay dead —
    the forced-fail flavor: host fallback keeps answering but the SLO
    availability gate must then FIRE, by design of obs/slo.py);
    ``poison_batches`` schedules malformed query micro-batches;
    ``dead_peers`` schedules ingest-feed timeouts on the load
    generator's upstream; ``clock_skews`` schedules clock faults at SLO
    evaluation points.
    """

    tenants: int = 2
    windows: int = 3
    requests_per_window: int = 4096
    objects: int = 512
    cache_size: int = 1 << 22
    sample_rows: int = 1024
    query_rows: int = 256
    replicas: int = 1
    seed: int = 7
    # per-tenant retrain cadence: tenant m retrains every cadence[m]
    # windows (empty -> every window for every tenant)
    cadence: Tuple[int, ...] = ()
    kills: int = 1
    device_deaths: int = 0
    device_death_burst: int = 2
    device_death_persist: bool = False
    poison_batches: int = 1
    dead_peers: int = 1
    clock_skews: int = 1
    num_iterations: int = 8
    num_leaves: int = 15
    max_bin: int = 63
    load_batch_rows: int = 64
    load_interval_s: float = 0.01
    slo: str = DEFAULT_SLO
    slo_window_s: float = 600.0
    checkpoint_dir: str = ""
    out: str = ""

    # -- validation -----------------------------------------------------
    def validate(self) -> "SoakScenario":
        if self.tenants < 1:
            raise LightGBMError("soak: tenants must be >= 1")
        if self.windows < 1:
            raise LightGBMError("soak: windows must be >= 1")
        if self.kills and self.windows < 2:
            raise LightGBMError(
                "soak: kills need windows >= 2 (a kill targets window "
                ">= 1 so a checkpoint exists to resume from)")
        if self.sample_rows < 64:
            raise LightGBMError("soak: sample_rows must be >= 64")
        if self.requests_per_window < 2 * self.sample_rows:
            raise LightGBMError(
                "soak: requests_per_window must be >= 2*sample_rows "
                "(labelable rows are trimmed to exactly sample_rows)")
        if self.cadence and len(self.cadence) != self.tenants:
            raise LightGBMError(
                "soak: cadence must be empty or one entry per tenant")
        if any(c < 1 for c in self.cadence):
            raise LightGBMError("soak: cadence entries must be >= 1")
        if self.kills and not any(
                len(self.schedule(m)) >= 2 for m in range(self.tenants)):
            raise LightGBMError(
                "soak: kills need at least one tenant with >= 2 "
                "scheduled retrain windows")
        return self

    # -- (de)serialization ----------------------------------------------
    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out["cadence"] = list(self.cadence)
        return out

    @classmethod
    def from_json(cls, doc: dict) -> "SoakScenario":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(doc) - known)
        if unknown:
            raise LightGBMError(
                f"soak scenario: unknown keys {unknown}")
        kw = dict(doc)
        if "cadence" in kw:
            kw["cadence"] = tuple(int(c) for c in kw["cadence"])
        return cls(**kw).validate()

    @classmethod
    def from_file(cls, path: str) -> "SoakScenario":
        with open(path) as fh:
            return cls.from_json(json.load(fh))

    @classmethod
    def from_config(cls, cfg) -> "SoakScenario":
        """Scenario from a Config's soak_* params; the LGBM_TPU_SOAK
        env var (a path or inline JSON object) overrides everything."""
        env = os.environ.get(ENV_SCENARIO, "").strip()
        if env:
            if env.startswith("{"):
                return cls.from_json(json.loads(env))
            return cls.from_file(env)
        path = str(getattr(cfg, "soak_scenario", "") or "")
        if path:
            return cls.from_file(path)
        kw = {}
        for name, attr in (
                ("tenants", "soak_tenants"),
                ("windows", "soak_windows"),
                ("requests_per_window", "soak_requests_per_window"),
                ("sample_rows", "soak_sample_rows"),
                ("replicas", "soak_replicas"),
                ("seed", "soak_seed"),
                ("kills", "soak_kills"),
                ("device_deaths", "soak_device_deaths"),
                ("poison_batches", "soak_poison_batches"),
                ("dead_peers", "soak_dead_peers"),
                ("clock_skews", "soak_clock_skews")):
            v = getattr(cfg, attr, None)
            if v is not None:
                kw[name] = int(v)
        slo = str(getattr(cfg, "soak_slo", "") or "")
        if slo:
            kw["slo"] = slo
        out = str(getattr(cfg, "soak_out", "") or "")
        if out:
            kw["out"] = out
        ckpt = str(getattr(cfg, "soak_checkpoint_dir", "") or "")
        if ckpt:
            kw["checkpoint_dir"] = ckpt
        return cls(**kw).validate()

    # -- retrain schedule ----------------------------------------------
    def tenant_cadence(self, m: int) -> int:
        return int(self.cadence[m]) if self.cadence else 1

    def schedule(self, m: int) -> List[int]:
        """The window indices tenant ``m`` retrains on (its cadence
        subsamples the global window sequence)."""
        cad = self.tenant_cadence(m)
        return [w for w in range(self.windows) if w % cad == 0]

    # -- workload -------------------------------------------------------
    def window_payload(self, tenant: int, window: int):
        """``PreppedWindow`` for (tenant, window): synth trace -> OPT
        labels -> gap-feature CSR, trimmed to exactly ``sample_rows``
        rows (shape-stable retrains).  Pure in (seed, tenant, window).
        ``window=-1`` is the bootstrap generation the fleet serves
        before window 0's retrain lands."""
        ca = _cache_admission()
        from ..pipeline.core import PreppedWindow
        seed = _hseed(self.seed, "trace", tenant, window)
        ids, sizes, costs = ca.synth_trace(
            self.requests_per_window, self.objects, seed=seed)
        to_cache, opt_ratio = ca.calculate_opt(
            ids, sizes, self.cache_size, self.requests_per_window)
        rng = np.random.default_rng(_hseed(self.seed, "sample",
                                           tenant, window))
        labels, indptr, indices, data = ca.derive_features(
            ids, sizes, costs, to_cache, self.cache_size,
            len(ids), 0, rng)
        n = len(labels)
        if n < self.sample_rows:
            raise LightGBMError(
                f"soak: window ({tenant},{window}) derived only {n} "
                f"labelable rows < sample_rows={self.sample_rows}; "
                "raise requests_per_window")
        keep = np.arange(n) >= (n - self.sample_rows)
        indptr, indices, data = ca._csr_row_subset(
            indptr, indices, data, keep)
        labels = labels[keep]
        return PreppedWindow(
            label=labels,
            csr=(indptr, indices, data, NUM_FEATURES),
            meta={"tenant": tenant, "window": window,
                  "opt_admit_ratio": round(float(opt_ratio), 4)})

    def query_block(self, tenant: int) -> np.ndarray:
        """Dense (query_rows, 53) f64 block the load thread replays for
        this tenant — densified rows of its bootstrap window."""
        from ..pipeline.core import densify_csr_rows
        pw = self.window_payload(tenant, -1)
        rows = min(int(self.query_rows), pw.num_rows)
        return densify_csr_rows(pw.csr, 0, rows)

    def train_params(self) -> dict:
        return {
            "boosting": "gbdt", "objective": "binary",
            "num_leaves": int(self.num_leaves),
            "max_bin": int(self.max_bin),
            "num_iterations": int(self.num_iterations),
            "learning_rate": 0.1, "min_data_in_leaf": 20,
            "verbosity": -1,
            # the byte-identical-resume contract (docs/Robustness.md)
            "pipeline_rebin": False, "window_policy": "fresh",
        }


# -- timeline ----------------------------------------------------------

def compile_timeline(sc: SoakScenario) -> List[FaultEvent]:
    """The scenario's chaos, placed deterministically.

    Pure in the scenario (sha256 of seed + kind + ordinals — no wall
    clock, no process RNG): the same scenario object always compiles
    to the same event list, which is what makes same-seed replay
    byte-identical.  Events sort by (kind, tenant, window, tick) so
    the listing itself is canonical.
    """
    ev: List[FaultEvent] = []
    # kills: distinct (tenant, window) points, window >= 1 within the
    # tenant's own retrain schedule, ranked by hash
    candidates = [(m, w) for m in range(sc.tenants)
                  for w in sc.schedule(m)[1:]]
    ranked = sorted(candidates,
                    key=lambda c: (_hu(sc.seed, "kill", c[0], c[1]), c))
    for i, (m, w) in enumerate(ranked[:sc.kills]):
        ev.append(FaultEvent(kind="kill", tenant=m, window=w, at=i,
                             site="soak.kill"))
    # transient (or persistent) device-death burst on the serving
    # dispatch path
    if sc.device_deaths > 0:
        after = 8 + int(_hu(sc.seed, "death") * 24)
        ev.append(FaultEvent(
            kind="device_death", tick=after,
            at=(-1 if sc.device_death_persist
                else sc.device_deaths * sc.device_death_burst),
            site="serve.fleet.dispatch"))
    # poisoned micro-batches: load-thread tick indices, ranked by hash
    ticks = sorted(range(4, 64),
                   key=lambda t: (_hu(sc.seed, "poison", t), t))
    for i, t in enumerate(sorted(ticks[:sc.poison_batches])):
        ev.append(FaultEvent(kind="poison", tick=t, at=i))
    # dead ingest peer: the load generator's upstream feed times out
    # for a contiguous run of ticks starting at a hash-placed tick
    if sc.dead_peers > 0:
        start = 2 + int(_hu(sc.seed, "peer") * 6)
        ev.append(FaultEvent(kind="dead_peer", tick=start,
                             at=sc.dead_peers, site="soak.load"))
    # clock skew at SLO evaluation points: index 0 = the run-start
    # stamp, index 1 = the verdict stamp
    for i in range(min(sc.clock_skews, 2)):
        ev.append(FaultEvent(kind="clock_skew", at=1 - i,
                             site="soak.clock"))
    ev.sort(key=lambda e: (e.kind, e.tenant, e.window, e.tick, e.at))
    return ev


def fault_spec(sc: SoakScenario,
               events: Optional[List[FaultEvent]] = None) -> str:
    """The single combined ``LGBM_TPU_FAULTS`` spec the driver arms
    ONCE up front (``faults.configure`` resets rules AND invocation
    counters, so the whole timeline must be one arming call)."""
    if events is None:
        events = compile_timeline(sc)
    parts: List[str] = []
    kills = [e for e in events if e.kind == "kill"]
    if kills:
        parts.append(f"soak.kill:n={len(kills)}")
    death = next((e for e in events if e.kind == "device_death"), None)
    if death is not None:
        if death.at < 0:
            parts.append(
                f"serve.fleet.dispatch:after={death.tick}:persist")
        else:
            parts.append(
                f"serve.fleet.dispatch:after={death.tick}:n={death.at}")
    peer = next((e for e in events if e.kind == "dead_peer"), None)
    if peer is not None:
        parts.append(f"soak.load:after={peer.tick}:n={peer.at}"
                     f":error=timeout")
    clocks = [e for e in events if e.kind == "clock_skew"]
    if clocks:
        lo = min(e.at for e in clocks)
        parts.append(f"soak.clock:after={lo}:n={len(clocks)}")
    return ",".join(parts)


def timeline_digest(sc: SoakScenario,
                    events: Optional[List[FaultEvent]] = None) -> str:
    """sha256 over the canonical timeline + armed spec — the replay
    identity two same-seed runs must agree on byte-for-byte."""
    if events is None:
        events = compile_timeline(sc)
    doc = {"spec": fault_spec(sc, events),
           "events": [e.to_json() for e in events]}
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def kill_points(events: List[FaultEvent]) -> Dict[int, List[int]]:
    """tenant -> sorted kill windows (driver-side lookup)."""
    out: Dict[int, List[int]] = {}
    for e in events:
        if e.kind == "kill":
            out.setdefault(e.tenant, []).append(e.window)
    return {m: sorted(ws) for m, ws in out.items()}


def poison_ticks(events: List[FaultEvent]) -> frozenset:
    return frozenset(e.tick for e in events if e.kind == "poison")
