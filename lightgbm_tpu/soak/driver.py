"""The soak conductor (docs/Soak.md).

Brings up the scenario's fleet (one bootstrap booster per tenant),
then overlaps three things until every tenant's retrain schedule
completes:

* per-tenant ``RetrainPipeline(server=fleet, tenant_id=m)`` threads
  hot-swapping each window's model into the shared ``FleetServer``,
  checkpointing every window (PR-8 atomics) and executing the
  timeline's scheduled kills — an ``InjectedFault`` raised from prep
  surfaces as ``PipelineError``, the driver resumes from the
  checkpoint, and after the run asserts the resumed tenant's final
  model is BYTE-identical to an uninterrupted reference run;
* a mixed-tenant query-load thread replaying each tenant's
  cache-admission feature rows through ``FleetServer.submit``,
  executing the timeline's poisoned micro-batches (malformed feature
  rows -> per-request isolation) and dead-ingest-peer timeouts
  (``soak.load``);
* the armed fault registry: device-death bursts fire inside the
  fleet's own ``serve.fleet.dispatch`` site (host fallback + breaker
  recovery), clock skews fire at the driver's two SLO clock stamps
  (``soak.clock``).

Every request outcome lands in the existing ``serve.fleet.*``
counters and the rolling mirror, which is what the verdict
(soak/report.py) evaluates the scenario's SLO spec against.

Thread discipline (jaxlint JL141/JL161): every worker takes the
parent ``SpanContext`` as its ``ctx`` parameter and re-installs it
first thing; no unbounded blocking primitives; every worker's closure
probes a registered fault site.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import obs
from ..basic import LightGBMError
from ..obs import tracing
from ..obs.rolling import RollingRegistry
# imported from .core (not the package re-export) so jaxlint's call
# graph can type `pipe` and see the tenant worker reach the armed
# pipeline.prep/pipeline.train fault sites through pipe.run (JL161)
from ..pipeline.core import PipelineError, RetrainPipeline
from ..robust import faults
from ..robust.checkpoint import load_pipeline_checkpoint
from ..robust.retry import CircuitBreaker
from ..serve.fleet import FleetServer
from .scenario import (NUM_FEATURES, SoakScenario, compile_timeline,
                       fault_spec, kill_points, poison_ticks,
                       timeline_digest)


class SoakDriver:
    """One scenario run -> an outcome dict for soak/report.py."""

    def __init__(self, scenario: SoakScenario,
                 workdir: Optional[str] = None):
        self.sc = scenario.validate()
        self.workdir = (workdir or scenario.checkpoint_dir
                        or tempfile.mkdtemp(prefix="lgbm_soak_"))
        self.events = compile_timeline(self.sc)
        self.spec = fault_spec(self.sc, self.events)
        self.digest = timeline_digest(self.sc, self.events)
        self._kill_points = kill_points(self.events)
        self._poison_ticks = poison_ticks(self.events)
        self.fleet: Optional[FleetServer] = None
        self._lock = threading.Lock()
        self._stop_load = threading.Event()
        self._killed: set = set()          # (tenant, window) fired
        self._kill_records: List[dict] = []
        self._tenant_errors: Dict[int, str] = {}
        self._window_log: Dict[int, List[dict]] = {}
        self._final_models: Dict[int, str] = {}
        self._futures: List = []
        self._load_stats = {"submitted": 0, "answered": 0,
                            "rejected": 0, "poison_sent": 0,
                            "dead_peer_timeouts": 0}
        self._clock_fired = 0

    # -- clock (soak.clock fault site) ---------------------------------
    def _clock(self) -> float:
        """Wall stamp for SLO bookkeeping; the timeline's clock-skew
        events fire here (main thread only, so the invocation index is
        deterministic: 0 = run start, 1 = verdict)."""
        try:
            faults.check("soak.clock")
        except faults.InjectedFault:
            with self._lock:
                self._clock_fired += 1
            obs.inc("soak.clock_skews")
        return time.time()

    # -- bring-up -------------------------------------------------------
    def _bootstrap(self) -> List:
        """Train each tenant's generation-0 booster (the model serving
        before window 0's retrain lands) on its bootstrap window."""
        boosters = []
        for m in range(self.sc.tenants):
            pipe = RetrainPipeline(self.sc.train_params(),
                                   warmup_rows=[])
            pipe.run([(m, -1)],
                     lambda key: self.sc.window_payload(*key))
            boosters.append(pipe.final_booster())
        return boosters

    def _build_fleet(self, boosters) -> FleetServer:
        sc = self.sc
        # fast re-probe so a transient device-death burst's dark time
        # stays small against the SLO window (docs/Robustness.md)
        fleet = FleetServer(
            boosters, replicas=sc.replicas,
            num_features=NUM_FEATURES,
            breaker_factory=lambda _replica: CircuitBreaker(
                failure_threshold=2, reprobe_interval_s=0.05))
        fleet.start()
        fleet.warmup(sorted({sc.load_batch_rows, sc.query_rows}))
        return fleet

    # -- tenant retrain thread -----------------------------------------
    def _prep(self, key):
        """Window ingestion + feature derivation for one (tenant,
        window).  Scheduled kills fire here: a timeline point probes
        ``soak.kill`` exactly once (driver bookkeeping, so the armed
        n= budget maps 1:1 onto scheduled points no matter how tenant
        threads interleave); the pipeline surfaces the injected fault
        as ``PipelineError`` and the driver resumes from the
        checkpoint."""
        m, w = key
        with self._lock:
            scheduled = (w in self._kill_points.get(m, ())
                         and (m, w) not in self._killed)
        if scheduled:
            faults.check("soak.kill")
        return self.sc.window_payload(m, w)

    def _on_window(self, res) -> None:
        m = int(res.meta.get("tenant", -1))
        with self._lock:
            self._window_log.setdefault(m, []).append(res.to_json())

    def _tenant_worker(self, m: int, ctx) -> None:
        tracing.set_current(ctx)
        sc = self.sc
        keys = [(m, w) for w in sc.schedule(m)]
        ckpt = os.path.join(self.workdir, f"tenant_{m}")
        params = sc.train_params()
        pipe = RetrainPipeline(params, server=self.fleet, tenant_id=m,
                               checkpoint_dir=ckpt, warmup_rows=[],
                               keep_boosters=False)
        for _attempt in range(2 * len(keys) + 2):
            try:
                pipe.run(keys, self._prep, on_window=self._on_window)
                break
            except PipelineError as e:
                pos = int(e.window)
                window = keys[pos][1] if pos < len(keys) else -1
                obs.inc("soak.kills")
                with self._lock:
                    self._killed.add((m, window))
                cp = load_pipeline_checkpoint(ckpt)
                rec = {"tenant": m, "window": window,
                       "payload_index": pos,
                       "checkpoint_window": (None if cp is None
                                             else int(cp.window)),
                       "resumed": False}
                try:
                    pipe = RetrainPipeline.resume(
                        ckpt, params, server=self.fleet, tenant_id=m,
                        warmup_rows=[], keep_boosters=False)
                    rec["resumed"] = True
                    obs.inc("soak.resumes")
                except LightGBMError as re_exc:
                    rec["resume_error"] = str(re_exc)
                    with self._lock:
                        self._kill_records.append(rec)
                    return
                with self._lock:
                    self._kill_records.append(rec)
            except LightGBMError as exc:
                with self._lock:
                    self._tenant_errors[m] = str(exc)
                return
        final = pipe.final_booster()
        if final is not None:
            with self._lock:
                self._final_models[m] = final.model_to_string()

    # -- query load thread ---------------------------------------------
    def _drain(self, keep: int) -> None:
        """Resolve finished futures, blocking (bounded) only when more
        than ``keep`` are still pending; a request the fleet failed —
        poison rows — counts as rejected."""
        with self._lock:
            pending = self._futures
            self._futures = []
        still = []
        for i, fut in enumerate(pending):
            if not fut.done() and (len(pending) - i) > keep:
                try:
                    fut.result(timeout=5.0)
                except Exception:
                    pass
            if fut.done():
                try:
                    fut.result()
                    ok = True
                except Exception:
                    ok = False
                with self._lock:
                    self._load_stats["answered" if ok
                                     else "rejected"] += 1
            else:
                still.append(fut)
        with self._lock:
            self._futures.extend(still)

    def _load_worker(self, ctx) -> None:
        tracing.set_current(ctx)
        sc = self.sc
        queries = [sc.query_block(m) for m in range(sc.tenants)]
        tick = 0
        while not self._stop_load.is_set():
            try:
                # the load generator's upstream feed: the timeline's
                # dead-ingest-peer run times out a contiguous span of
                # ticks (only this thread probes the site, so the
                # armed after=/n= indices ARE tick numbers)
                faults.check("soak.load")
            except (faults.InjectedFault, TimeoutError, OSError):
                with self._lock:
                    self._load_stats["dead_peer_timeouts"] += 1
                obs.inc("soak.dead_peer_timeouts")
                tick += 1
                self._stop_load.wait(sc.load_interval_s)
                continue
            m = tick % sc.tenants
            q = queries[m]
            rows = min(sc.load_batch_rows, q.shape[0])
            lo = (tick * rows) % max(1, q.shape[0] - rows + 1)
            batch = q[lo:lo + rows]
            if tick in self._poison_ticks:
                # malformed micro-batch: truncated feature rows, which
                # the fleet must isolate per-request (input_errors /
                # poisoned_batches), never poisoning neighbors
                batch = np.ascontiguousarray(
                    batch[:, :max(1, NUM_FEATURES // 8)])
                with self._lock:
                    self._load_stats["poison_sent"] += 1
                obs.inc("soak.poison_sent")
            fut = self.fleet.submit(m, batch)
            with self._lock:
                self._load_stats["submitted"] += 1
                self._futures.append(fut)
            self._drain(keep=64)
            tick += 1
            self._stop_load.wait(sc.load_interval_s)
        self._drain(keep=0)

    # -- byte-identity reference ---------------------------------------
    def _verify_kills(self) -> List[dict]:
        """For every tenant that took a kill: an uninterrupted
        reference pipeline (same params/payloads, no serving, faults
        disarmed by the caller) must produce a byte-identical final
        model — the check_faults.py contract at fleet scale."""
        out = []
        for m in sorted({r["tenant"] for r in self._kill_records}):
            keys = [(m, w) for w in self.sc.schedule(m)]
            ref = RetrainPipeline(self.sc.train_params(),
                                  warmup_rows=[])
            ref.run(keys, lambda key: self.sc.window_payload(*key))
            ref_str = ref.final_booster().model_to_string()
            got = self._final_models.get(m)
            out.append({
                "tenant": m,
                "kills": sorted(r["window"] for r in
                                self._kill_records
                                if r["tenant"] == m),
                "resumed": all(r["resumed"] for r in
                               self._kill_records
                               if r["tenant"] == m),
                "byte_identical": got is not None and got == ref_str,
            })
        return out

    # -- run ------------------------------------------------------------
    def run(self) -> dict:
        sc = self.sc
        os.makedirs(self.workdir, exist_ok=True)
        stream_path = os.path.join(self.workdir, "stream.jsonl")
        # the SLO window must fit in the rolling ring
        # (slo.evaluate raises SloSpecError past capacity)
        buckets = max(128, int(sc.slo_window_s) + 60)
        obs.configure(enabled=True,
                      rolling=RollingRegistry(bucket_seconds=1.0,
                                              num_buckets=buckets),
                      stream_path=stream_path,
                      export_interval_s=0.5)
        faults.configure(self.spec)
        started_unix = self._clock()
        t0 = time.perf_counter()
        outcome: dict = {
            "scenario": sc.to_json(),
            "fault_spec": self.spec,
            "timeline": [e.to_json() for e in self.events],
            "timeline_digest": self.digest,
            "workdir": self.workdir,
            "started_unix": round(started_unix, 3),
        }
        try:
            boosters = self._bootstrap()
            self.fleet = self._build_fleet(boosters)
            root = (tracing.SpanContext(tracing.new_id())
                    if tracing.enabled() else None)
            load = threading.Thread(target=self._load_worker,
                                    args=(root,), name="lgbm-soak-load",
                                    daemon=True)
            load.start()
            workers = []
            for m in range(sc.tenants):
                t = threading.Thread(target=self._tenant_worker,
                                     args=(m, root),
                                     name=f"lgbm-soak-tenant-{m}",
                                     daemon=True)
                t.start()
                workers.append(t)
            for t in workers:
                t.join(timeout=600.0)
            alive = [t.name for t in workers if t.is_alive()]
            if alive:
                self._tenant_errors[-1] = \
                    f"tenant threads still alive: {alive}"
            self._stop_load.set()
            load.join(timeout=60.0)
            # evaluate the SLO on live state (before reference runs
            # pollute counters), then snapshot everything
            from ..obs import slo as slo_mod
            evaluated_unix = self._clock()
            slo_report = slo_mod.evaluate(sc.slo, now=evaluated_unix)
            obs.flush()
            export = (obs.summary().get("export") or {})
            snap = obs.registry().snapshot()
            counters = {k: v for k, v in snap["counters"].items()
                        if k.split(".")[0] in ("serve", "fault",
                                               "soak", "pipeline")}
            fault_counts = dict(faults.counts())
        finally:
            if self.fleet is not None:
                self.fleet.stop()
            faults.clear()
        byte_identity = self._verify_kills()
        with self._lock:
            outcome.update({
                "elapsed_s": round(time.perf_counter() - t0, 3),
                "evaluated_unix": round(evaluated_unix, 3),
                "slo": slo_report,
                "windows": {str(m): v for m, v in
                            sorted(self._window_log.items())},
                "kills": list(self._kill_records),
                "byte_identity": byte_identity,
                "tenant_errors": {str(m): v for m, v in
                                  self._tenant_errors.items()},
                "load": dict(self._load_stats),
                "clock_faults_fired": self._clock_fired,
                "counters": counters,
                "export": export,
                "fault_counts": fault_counts,
            })
        return outcome


def run_scenario(sc: SoakScenario,
                 workdir: Optional[str] = None) -> dict:
    """Convenience: drive one scenario and return its outcome."""
    return SoakDriver(sc, workdir=workdir).run()
