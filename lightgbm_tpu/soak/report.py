"""Soak verdicts (docs/Soak.md).

Turns a driver outcome into the gated verdict document:

* the scenario's SLO spec evaluated by ``obs/slo.py`` from the
  rolling windows — availability *through* retrains and kills (dark
  time accounted via the degraded-replica gauge integral), the p95
  bound, the burn rate;
* harness-level gates the SLO engine cannot see — every scheduled
  kill resumed and reconverged to a byte-identical model, every
  same-shape swap after window 0 was a zero-retrace index write,
  every scheduled chaos event actually fired, the exporter dropped
  nothing, and the throughput figure
  (``cache_admission_train_s_per_1M_sampled_rows``) against the
  fork's committed 125.4 s / 20M-row reference.

Off-TPU the verdict carries ``chip_pending=true`` and the throughput
gate is informational (the number validates plumbing, not the chip —
the BENCH_r06 honesty convention).

The verdict is written with a plain ``open().write`` — it carries
wall timings by design, so it must NOT go through the deterministic
artifact writers jaxlint JL131 guards (``atomic_write_text`` & co are
reserved for byte-reproducible artifacts).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from .scenario import REFERENCE_S_PER_1M_ROWS, SoakScenario

SCHEMA_NAME = "lightgbm-tpu-soak"
SCHEMA_VERSION = 1

# off-reference slack for the on-chip throughput gate; off-chip the
# gate is informational (chip_pending)
THROUGHPUT_SLACK = 1.5


def _slo_json(slo) -> dict:
    if slo is None:
        return {}
    return slo if isinstance(slo, dict) else slo.to_json()


def _schedule(sc_doc: dict, m: int) -> List[int]:
    cadence = sc_doc.get("cadence") or []
    cad = int(cadence[m]) if cadence else 1
    return [w for w in range(int(sc_doc["windows"])) if w % cad == 0]


def build_verdict(outcome: dict, *,
                  throughput_slack: float = THROUGHPUT_SLACK) -> dict:
    """The gated verdict for one driver outcome (pure function of the
    outcome + backend, so tests can feed synthetic outcomes)."""
    import jax

    sc = outcome["scenario"]
    chip_pending = jax.default_backend() != "tpu"
    slo = _slo_json(outcome.get("slo"))
    objectives = {o.get("name"): o for o in slo.get("objectives", [])}
    timeline = outcome.get("timeline", [])
    windows: Dict[str, List[dict]] = outcome.get("windows", {})
    load = outcome.get("load", {})
    counters = outcome.get("counters", {})
    export = outcome.get("export", {})
    gates: Dict[str, dict] = {}

    # -- SLO-engine gates ----------------------------------------------
    avail = objectives.get("availability", {})
    gates["availability"] = {
        "ok": bool(avail.get("ok", False)),
        "target": avail.get("target"),
        "observed": avail.get("observed"),
        "dark_fraction": (slo.get("counts") or {}).get("dark_fraction"),
    }
    gates["slo"] = {"ok": bool(slo.get("ok", False)),
                    "objectives": sorted(objectives)}

    # -- completion -----------------------------------------------------
    want = {str(m): len(_schedule(sc, m))
            for m in range(int(sc["tenants"]))}
    got = {m: len(v) for m, v in windows.items()}
    gates["completed"] = {
        "ok": (not outcome.get("tenant_errors")
               and all(got.get(m, 0) == n for m, n in want.items())),
        "windows_expected": want, "windows_trained": got,
        "tenant_errors": outcome.get("tenant_errors", {}),
    }

    # -- resume byte-identity per kill ---------------------------------
    kills = outcome.get("kills", [])
    ident = outcome.get("byte_identity", [])
    scheduled_kills = sum(1 for e in timeline if e["kind"] == "kill")
    gates["resume_byte_identity"] = {
        "ok": (len(kills) == scheduled_kills
               and all(r.get("resumed") for r in kills)
               and all(r.get("byte_identical") for r in ident)
               and len(ident) == len({r["tenant"] for r in kills})),
        "scheduled": scheduled_kills, "fired": len(kills),
        "tenants": ident,
    }

    # -- zero-retrace swaps after window 0 -----------------------------
    per_tenant = {}
    zr_ok = True
    for m, results in windows.items():
        later = [r for r in results if int(r.get("window", 0)) >= 1]
        retraced = [r["window"] for r in later
                    if r.get("swap_same_shape") is not True]
        per_tenant[m] = {"swaps": len(results),
                         "after_w0": len(later),
                         "retraced_windows": retraced}
        zr_ok = zr_ok and not retraced
    gates["zero_retrace_swaps"] = {
        "ok": zr_ok,
        "per_tenant": per_tenant,
        "fleet_shape_changes":
            counters.get("serve.fleet.swap_shape_changes", 0),
    }

    # -- scheduled chaos actually fired --------------------------------
    fired = {
        "kills": len(kills),
        "dead_peer_timeouts": load.get("dead_peer_timeouts", 0),
        "poison_sent": load.get("poison_sent", 0),
        "clock_faults": outcome.get("clock_faults_fired", 0),
        "device_faults": counters.get("fault.serve.fleet.dispatch", 0),
    }
    want_chaos = {
        "kills": scheduled_kills,
        "dead_peer_timeouts": next(
            (e["at"] for e in timeline if e["kind"] == "dead_peer"), 0),
        "clock_faults": sum(1 for e in timeline
                            if e["kind"] == "clock_skew"),
    }
    chaos_ok = all(fired[k] == v for k, v in want_chaos.items())
    if any(e["kind"] == "poison" for e in timeline):
        # poison batches fire only if the load loop reached their tick;
        # when any did, the fleet must have isolated them per-request
        chaos_ok = chaos_ok and (
            fired["poison_sent"] == 0
            or counters.get("serve.fleet.input_errors", 0) > 0)
    gates["chaos_fired"] = {"ok": chaos_ok, "fired": fired,
                            "scheduled": want_chaos}

    # -- telemetry integrity -------------------------------------------
    gates["export"] = {
        "ok": (export.get("dropped", 0) == 0
               and export.get("write_errors", 0) == 0),
        "stats": export,
    }

    # -- throughput vs the fork's committed reference ------------------
    train_s = rows = 0.0
    for results in windows.values():
        for r in results:
            train_s += float(r.get("train_s", 0.0))
            rows += float(r.get("rows_trained", 0))
    value = (train_s / (rows / 1e6)) if rows else None
    gates["throughput"] = {
        "ok": bool(chip_pending or (value is not None
                                    and value <= REFERENCE_S_PER_1M_ROWS
                                    * throughput_slack)),
        "train_s_per_1M_sampled_rows":
            None if value is None else round(value, 3),
        "reference_s_per_1M": round(REFERENCE_S_PER_1M_ROWS, 3),
        "reference": "125.4 s / 20M rows (ROADMAP.md)",
        "chip_pending": chip_pending,
    }

    verdict = {
        "schema": SCHEMA_NAME,
        "schema_version": SCHEMA_VERSION,
        "scenario": sc,
        "fault_spec": outcome.get("fault_spec", ""),
        "timeline": timeline,
        "timeline_digest": outcome.get("timeline_digest", ""),
        "slo": slo,
        "gates": gates,
        "ok": all(g["ok"] for g in gates.values()),
        "chip_pending": chip_pending,
        "kills": kills,
        "load": load,
        "counters": counters,
        "elapsed_s": outcome.get("elapsed_s"),
        "started_unix": outcome.get("started_unix"),
        "evaluated_unix": outcome.get("evaluated_unix"),
    }
    return verdict


def strip_volatile(verdict: dict) -> dict:
    """The replay-stable projection of a verdict: what two same-seed
    runs must agree on byte-for-byte (wall timings, observed latencies
    and counter magnitudes vary run to run; the timeline, the armed
    spec, which gates passed, and the kill/identity records must
    not)."""
    return {
        "schema": verdict.get("schema"),
        "schema_version": verdict.get("schema_version"),
        "scenario": verdict.get("scenario"),
        "fault_spec": verdict.get("fault_spec"),
        "timeline": verdict.get("timeline"),
        "timeline_digest": verdict.get("timeline_digest"),
        "gates": {name: bool(g.get("ok"))
                  for name, g in verdict.get("gates", {}).items()},
        "kills": sorted(
            ({"tenant": r.get("tenant"), "window": r.get("window"),
              "payload_index": r.get("payload_index"),
              "checkpoint_window": r.get("checkpoint_window"),
              "resumed": r.get("resumed")}
             for r in verdict.get("kills", [])),
            key=lambda r: (r["tenant"], r["window"])),
        "byte_identity": verdict.get("gates", {})
            .get("resume_byte_identity", {}).get("tenants"),
        "ok": verdict.get("ok"),
        "chip_pending": verdict.get("chip_pending"),
    }


def write_verdict(verdict: dict, path: str) -> str:
    """Plain (non-atomic-artifact) write — see module docstring."""
    with open(path, "w") as fh:
        fh.write(json.dumps(verdict, indent=2, sort_keys=True,
                            default=str))
        fh.write("\n")
    return path


def run_and_report(sc: SoakScenario,
                   workdir: Optional[str] = None) -> dict:
    """Drive the scenario, build its verdict, honor ``scenario.out``."""
    from .driver import SoakDriver
    outcome = SoakDriver(sc, workdir=workdir).run()
    verdict = build_verdict(outcome)
    if sc.out:
        write_verdict(verdict, sc.out)
    return verdict
