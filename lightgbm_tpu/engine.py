"""Training entry points ``train()`` and ``cv()``
(reference ``python-package/lightgbm/engine.py:19-501``)."""

from __future__ import annotations

import collections
from typing import List

import numpy as np

from . import callback as callback_mod
from . import obs
from .basic import Booster, Dataset
from .config import normalize_params
from .utils.log import LightGBMError, log_warning

__all__ = ["train", "cv"]


def steps_to_boundary(i: int, freq: int) -> int:
    """Iterations to run, starting at ``i``, to land on (and include)
    the next iteration j >= i with ``(j + 1) % freq == 0`` — the shared
    chunk cap that keeps fused driving's metric/snapshot cadence
    byte-identical to the per-iteration loop (also used by cli.py)."""
    return ((freq - ((i + 1) % freq)) % freq) + 1


def _dedupe_callbacks(callbacks) -> List:
    """Explicit ordered dedupe of user callbacks (identity/equality based,
    first occurrence wins) — replaces the old ``set()`` which iterated in
    hash order."""
    out: List = []
    for cb in (callbacks or []):
        if cb not in out:
            out.append(cb)
    return out


def train(params, train_set, num_boost_round=100, valid_sets=None,
          valid_names=None, fobj=None, feval=None, init_model=None,
          feature_name="auto", categorical_feature="auto",
          early_stopping_rounds=None, evals_result=None, verbose_eval=True,
          learning_rates=None, keep_training_booster=False, callbacks=None):
    """Train one booster (reference engine.py:19-240)."""
    params = normalize_params(params)
    if fobj is not None:
        params["objective"] = "none"
    num_boost_round = params.pop("num_iterations", num_boost_round) \
        if "num_iterations" in params else num_boost_round
    if num_boost_round <= 0:
        raise ValueError("num_boost_round should be greater than zero.")

    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    if feature_name != "auto":
        train_set.set_feature_name(feature_name)
    if categorical_feature != "auto":
        train_set.set_categorical_feature(categorical_feature)
    train_set.params = {**params, **train_set.params} \
        if train_set._handle is None else train_set.params

    init_iter = 0
    booster = Booster(params=params, train_set=train_set)
    if init_model is not None:
        booster = _continue_from(init_model, params, train_set)
        init_iter = booster._gbdt.num_init_iteration

    is_valid_contain_train = False
    train_data_name = "training"
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        valid_names = valid_names or [f"valid_{i}"
                                      for i in range(len(valid_sets))]
        for i, vs in enumerate(valid_sets):
            if vs is train_set:
                is_valid_contain_train = True
                train_data_name = valid_names[i]
                continue
            if vs.reference is None:
                vs.reference = train_set
            booster.add_valid(vs, valid_names[i])

    # user callbacks keep their insertion order (a set iterates in hash
    # order — nondeterministic across runs for same-`order` callbacks);
    # duplicates are removed explicitly, first occurrence wins
    cbs = _dedupe_callbacks(callbacks)
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.append(callback_mod.early_stopping(
            early_stopping_rounds,
            verbose=bool(verbose_eval)))
    if verbose_eval is True:
        cbs.append(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval is not False:
        cbs.append(callback_mod.print_evaluation(verbose_eval))
    if evals_result is not None:
        cbs.append(callback_mod.record_evaluation(evals_result))
    if learning_rates is not None:
        cbs.append(callback_mod.reset_parameter(learning_rate=learning_rates))
    if obs.enabled():
        # telemetry hooks: CallbackEnv-compatible pair timing each
        # iteration and sampling device memory (docs/Observability.md)
        cbs.extend(obs.iteration_hooks())

    cbs_before = [cb for cb in cbs
                  if getattr(cb, "before_iteration", False)]
    cbs_after = [cb for cb in cbs
                 if not getattr(cb, "before_iteration", False)]
    # stable sort: equal `order` preserves insertion order
    cbs_before.sort(key=lambda cb: getattr(cb, "order", 0))
    cbs_after.sort(key=lambda cb: getattr(cb, "order", 0))

    metric_freq = int(params.get("metric_freq", 1) or 1)
    end_iter = init_iter + num_boost_round
    # fused driving: when every callback is either pure telemetry or
    # only acts on eval-carrying iterations, whole stretches between
    # evaluation boundaries run as ONE device dispatch
    # (GBDT.train_chunked).  Any opaque user callback (or a
    # before-iteration one like reset_parameter) forces the
    # per-iteration loop — its CallbackEnv cadence is the contract.
    fused_cap = max(int(getattr(booster._gbdt.config, "fused_chunk",
                                20)), 0)
    cbs_opaque = any(
        not (getattr(cb, "eval_cadence_only", False)
             or getattr(cb, "obs_hook", False))
        for cb in cbs_before + cbs_after)
    has_eval = (bool(booster.name_valid_sets) or is_valid_contain_train
                or feval is not None)
    # an eval-requiring callback (early_stopping) with no eval data is a
    # misconfiguration; stay per-iteration so its error fires at
    # iteration 0 instead of after a whole fused run
    needs_eval_cb = any(getattr(cb, "requires_eval", False)
                        for cb in cbs_before + cbs_after)
    can_fuse = (fobj is None and fused_cap > 1 and not cbs_opaque
                and not (needs_eval_cb and not has_eval)
                and booster._gbdt.fused_eligible())

    evaluation_result_list = []
    i = init_iter
    while i < end_iter:
        for cb in cbs_before:
            cb(callback_mod.CallbackEnv(
                model=booster, params=params, iteration=i,
                begin_iteration=init_iter,
                end_iteration=end_iter,
                evaluation_result_list=None))
        step = 1
        if can_fuse:
            step = end_iter - i
            if has_eval:
                # up to and including the next iteration whose results
                # feed callbacks — eval cadence is preserved exactly
                step = min(step, steps_to_boundary(i, metric_freq))
        if step > 1:
            before_it = booster._gbdt.iter
            finished = booster._gbdt.train_chunked(
                step, chunk=min(step, fused_cap))
            advanced = max(booster._gbdt.iter - before_it, 1)
        else:
            finished = booster.update(fobj=fobj)
            advanced = 1
        i_done = i + advanced - 1

        evaluation_result_list = []
        if (i_done + 1) % metric_freq == 0 or i_done == end_iter - 1:
            if is_valid_contain_train:
                evaluation_result_list.extend(
                    [(train_data_name, n, v, b)
                     for _, n, v, b in booster.eval_train(feval)])
            evaluation_result_list.extend(booster.eval_valid(feval))
        try:
            for cb in cbs_after:
                cb(callback_mod.CallbackEnv(
                    model=booster, params=params, iteration=i_done,
                    begin_iteration=init_iter,
                    end_iteration=end_iter,
                    evaluation_result_list=evaluation_result_list))
        except callback_mod.EarlyStopException as es:
            booster.best_iteration = es.best_iteration + 1
            evaluation_result_list = es.best_score
            break
        i += advanced
        if finished:
            break

    booster.best_score = collections.defaultdict(collections.OrderedDict)
    for rec in (evaluation_result_list or []):
        booster.best_score[rec[0]][rec[1]] = rec[2]
    if not keep_training_booster:
        booster._train_set = None
    try:
        obs.flush()   # write metrics/trace files when paths are configured
    except OSError as e:
        # telemetry is best-effort: a bad metrics/trace path must not
        # destroy a fully trained booster
        log_warning(f"failed to write telemetry output: {e}")
    return booster


def _continue_from(init_model, params, train_set):
    """Continued training: load model, use its predictions as init score
    (reference boosting.cpp:15-28, engine.py init_model handling)."""
    if isinstance(init_model, str):
        prev = Booster(model_file=init_model, params=params)
    elif isinstance(init_model, Booster):
        prev = Booster(model_str=init_model.model_to_string(), params=params)
    else:
        raise TypeError("init_model should be a Booster or a model file path")
    train_set.construct()
    raw_source = train_set.raw
    if raw_source is None:
        raise LightGBMError(
            "continued training needs raw data: construct the Dataset with "
            "free_raw_data=False")
    init_score = prev._gbdt.predict_raw(raw_source)
    md = train_set._handle.metadata
    # predict_raw returns (num_model, N); Metadata stores class-major
    # [k*N + i] like the reference (basic.py _set_init_score_by_predictor
    # regroups to exactly this layout)
    md.set_init_score(init_score.reshape(-1))
    booster = Booster(params=params, train_set=train_set)
    booster._gbdt.models = list(prev._gbdt.models)
    booster._gbdt.num_init_iteration = prev._gbdt.num_iterations()
    booster._gbdt.iter = 0
    return booster


# ---------------------------------------------------------------------------
# cross validation (reference engine.py:262-501)
# ---------------------------------------------------------------------------

def _make_n_folds(full_data, folds, nfold, params, seed, stratified,
                  shuffle):
    full_data.construct()
    num_data = full_data.num_data()
    if folds is not None:
        if not hasattr(folds, "__iter__") and not hasattr(folds, "split"):
            raise AttributeError(
                "folds should be a generator or iterator of (train_idx, "
                "test_idx) tuples or scikit-learn splitter object")
        if hasattr(folds, "split"):
            group = full_data.get_group()
            group_info = (np.repeat(np.arange(len(group)), group)
                          if group is not None else None)
            folds = folds.split(X=np.zeros(num_data),
                                y=full_data.get_label(), groups=group_info)
    else:
        group = full_data.get_group()
        if group is not None:
            # group-aware folds: split by query
            ng = len(group)
            rng = np.random.RandomState(seed)
            order = rng.permutation(ng) if shuffle else np.arange(ng)
            boundaries = np.concatenate([[0], np.cumsum(group)])
            flocs = np.array_split(order, nfold)
            folds = []
            for f in flocs:
                test_idx = np.concatenate(
                    [np.arange(boundaries[q], boundaries[q + 1])
                     for q in f]) if len(f) else np.empty(0, np.int64)
                mask = np.ones(num_data, bool)
                mask[test_idx.astype(np.int64)] = False
                folds.append((np.nonzero(mask)[0], test_idx.astype(np.int64)))
        elif stratified:
            from sklearn.model_selection import StratifiedKFold
            skf = StratifiedKFold(n_splits=nfold, shuffle=shuffle,
                                  random_state=seed if shuffle else None)
            folds = list(skf.split(np.zeros(num_data),
                                   full_data.get_label()))
        else:
            rng = np.random.RandomState(seed)
            order = rng.permutation(num_data) if shuffle \
                else np.arange(num_data)
            folds = [(np.setdiff1d(order, chunk, assume_unique=False), chunk)
                     for chunk in np.array_split(order, nfold)]
    ret = []
    for train_idx, test_idx in folds:
        train_sub = full_data.subset(np.sort(train_idx))
        test_sub = full_data.subset(np.sort(test_idx))
        ret.append((train_sub, test_sub))
    return ret


def cv(params, train_set, num_boost_round=100, folds=None, nfold=5,
       stratified=True, shuffle=True, metrics=None, fobj=None, feval=None,
       init_model=None, feature_name="auto", categorical_feature="auto",
       early_stopping_rounds=None, fpreproc=None, verbose_eval=None,
       show_stdv=True, seed=0, callbacks=None):
    """K-fold cross validation; returns {metric-mean: [...],
    metric-stdv: [...]} (reference engine.py:262-501)."""
    params = normalize_params(params)
    if fobj is not None:
        params["objective"] = "none"
    if metrics is not None:
        params["metric"] = metrics
    if train_set.get_label() is None and train_set.label is None:
        raise LightGBMError("labels should not be None in cv")
    if stratified and train_set.get_group() is not None:
        stratified = False
    if stratified:
        label = train_set.construct().get_label()
        # stratification needs classification-style labels
        if len(np.unique(label)) > max(2, int(params.get("num_class", 1))) \
                and params.get("objective", "regression").startswith(
                    ("regression", "huber", "fair", "poisson", "quantile",
                     "mape", "gamma", "tweedie")):
            stratified = False

    folds_data = _make_n_folds(train_set, folds, nfold, params, seed,
                               stratified, shuffle)
    boosters = []
    for train_sub, test_sub in folds_data:
        if fpreproc is not None:
            train_sub, test_sub, tparams = fpreproc(train_sub, test_sub,
                                                    params.copy())
        else:
            tparams = params
        bst = Booster(params=tparams, train_set=train_sub)
        bst.add_valid(test_sub, "valid")
        boosters.append(bst)

    results = collections.defaultdict(list)
    cbs = _dedupe_callbacks(callbacks)
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.append(callback_mod.early_stopping(early_stopping_rounds,
                                               verbose=False))
    if verbose_eval is True:
        cbs.append(callback_mod.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and verbose_eval not in (False, None):
        cbs.append(callback_mod.print_evaluation(verbose_eval, show_stdv))
    # stable sort keeps insertion order for equal `order`
    cbs = sorted(cbs, key=lambda cb: getattr(cb, "order", 0))

    class _CVBooster:
        def __init__(self, boosters):
            self.boosters = boosters

        def reset_parameter(self, new_params):
            for b in self.boosters:
                b.reset_parameter(new_params)

    cvbooster = _CVBooster(boosters)
    for i in range(num_boost_round):
        for bst in boosters:
            bst.update(fobj=fobj)
        merged = collections.defaultdict(list)
        order = []
        bigger = {}
        for bst in boosters:
            for dname, mname, val, b in bst.eval_valid(feval):
                key = f"{dname} {mname}"
                if key not in merged:
                    order.append(key)
                merged[key].append(val)
                bigger[key] = b
        agg = [(k.split(" ", 1)[0], k.split(" ", 1)[1],
                float(np.mean(merged[k])), bigger[k],
                float(np.std(merged[k]))) for k in order]
        for _, name, mean, _, std in agg:
            results[f"{name}-mean"].append(mean)
            results[f"{name}-stdv"].append(std)
        try:
            for cb in cbs:
                cb(callback_mod.CallbackEnv(
                    model=cvbooster, params=params, iteration=i,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=agg))
        except callback_mod.EarlyStopException as es:
            for k in results:
                results[k] = results[k][:es.best_iteration + 1]
            break
    return dict(results)
